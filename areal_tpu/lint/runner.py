"""Orchestrates the checkers over a file set and applies the
allowlist. Two passes: parse + collect cross-file facts (loop-only
registries, env-knob uses, wire/metrics/chaos registries), then check.
Registry-backed dead-entry passes are gated on the scan covering the
registry module itself, so linting one file never misreports a whole
registry dead."""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional, Set

from areal_tpu.lint import (
    blocking_async,
    chaos,
    env_knobs,
    locks,
    loop_only,
    metrics,
    rpc_discipline,
    wire_contract,
    wire_schema,
)
from areal_tpu.lint.common import (
    Finding,
    Module,
    apply_allowlist,
    iter_py_files,
    parse_allowlist,
    parse_module,
)

ALL_CHECKERS = (
    "loop-only", "blocking-async", "env-knob", "wire-schema",
    "wire-contract", "metrics-registry", "chaos-registry", "lock-order",
    "rpc-discipline",
)

# The linter's own test corpus: fixture sources are deliberately full
# of seeded contract violations (fake metric names, unknown routes,
# undeclared chaos points), so the cross-process checkers must not
# judge them against the REAL registries.
LINT_FIXTURE_PREFIX = "tests/lint/"


@dataclasses.dataclass
class LintConfig:
    root: str  # repo root all finding paths are relative to
    allowlist_path: Optional[str] = None
    env_cfg: Optional[env_knobs.EnvKnobConfig] = None
    metrics_cfg: Optional[metrics.MetricsConfig] = None
    chaos_cfg: Optional[chaos.ChaosConfig] = None
    wire_cfg: Optional[wire_contract.WireConfig] = None
    lock_cfg: Optional[locks.LockConfig] = None
    rpc_cfg: Optional[rpc_discipline.RpcConfig] = None
    # None = auto: dead-knob check runs iff the scan covers the
    # registry module (linting one file must not misreport the whole
    # registry as dead). Same gating applies to the metrics/chaos/wire
    # global passes, always in auto mode.
    check_dead_knobs: Optional[bool] = None
    wire_constants_rel: str = wire_schema.CONSTANTS_REL
    checkers: Set[str] = dataclasses.field(
        default_factory=lambda: set(ALL_CHECKERS)
    )


def run_lint(paths: List[str], cfg: LintConfig) -> List[Finding]:
    files = iter_py_files(paths)
    modules: List[Module] = []
    findings: List[Finding] = []
    for path in files:
        mod, err = parse_module(path, cfg.root)
        if err is not None:
            findings.append(err)
        if mod is not None:
            modules.append(mod)

    env_cfg = cfg.env_cfg
    if env_cfg is None and "env-knob" in cfg.checkers:
        env_cfg = env_knobs.default_config()
    metrics_cfg = cfg.metrics_cfg
    if metrics_cfg is None and "metrics-registry" in cfg.checkers:
        metrics_cfg = metrics.default_config()
    chaos_cfg = cfg.chaos_cfg
    if chaos_cfg is None and "chaos-registry" in cfg.checkers:
        chaos_cfg = chaos.default_config()
    wire_cfg = cfg.wire_cfg
    if wire_cfg is None and "wire-contract" in cfg.checkers:
        wire_cfg = wire_contract.default_config()
    lock_cfg = cfg.lock_cfg
    if lock_cfg is None and "lock-order" in cfg.checkers:
        lock_cfg = locks.default_config()
    rpc_cfg = cfg.rpc_cfg
    if rpc_cfg is None and "rpc-discipline" in cfg.checkers:
        rpc_cfg = rpc_discipline.default_config()

    # -- pass 1: cross-file facts ---------------------------------------
    registries: Dict[str, Dict] = {}  # rel -> loop-only registry
    hint_map: Dict[str, Set[str]] = {}  # attr -> instance hint names
    env_registry_mod: Optional[Module] = None
    metrics_registry_mod: Optional[Module] = None
    chaos_registry_mod: Optional[Module] = None
    wire_registry_mod: Optional[Module] = None
    rpc_registry_mod: Optional[Module] = None
    for mod in modules:
        if "loop-only" in cfg.checkers:
            reg = loop_only.collect_registry(mod)
            if reg:
                registries[mod.rel] = reg
                for spec in reg.values():
                    if not isinstance(spec, dict):
                        continue
                    for attr in spec.get("attrs", ()):
                        hint_map.setdefault(attr, set()).update(
                            spec.get("instance_hints", ())
                        )
        if env_cfg is not None and mod.rel == env_cfg.registry_rel:
            env_registry_mod = mod
        if metrics_cfg is not None and mod.rel == metrics_cfg.registry_rel:
            metrics_registry_mod = mod
        if chaos_cfg is not None and mod.rel == chaos_cfg.registry_rel:
            chaos_registry_mod = mod
        if wire_cfg is not None and mod.rel == wire_cfg.registry_rel:
            wire_registry_mod = mod
        if rpc_cfg is not None and mod.rel == rpc_cfg.registry_rel:
            rpc_registry_mod = mod

    # -- pass 2: checks --------------------------------------------------
    env_uses: Dict[str, int] = {}
    metric_uses: Dict[str, int] = {}
    chaos_uses: Dict[str, int] = {}
    wire_acc = wire_contract.WireAcc()
    for mod in modules:
        if "blocking-async" in cfg.checkers:
            findings.extend(blocking_async.check(mod))
        if "wire-schema" in cfg.checkers:
            findings.extend(wire_schema.check(mod, cfg.wire_constants_rel))
        if "env-knob" in cfg.checkers and env_cfg is not None:
            findings.extend(env_knobs.check(mod, env_cfg, env_uses))
        is_lint_fixture = mod.rel.startswith(LINT_FIXTURE_PREFIX)
        if "metrics-registry" in cfg.checkers and metrics_cfg is not None \
                and not is_lint_fixture:
            findings.extend(metrics.check(mod, metrics_cfg, metric_uses))
        if "chaos-registry" in cfg.checkers and chaos_cfg is not None \
                and not is_lint_fixture:
            findings.extend(chaos.check(mod, chaos_cfg, chaos_uses))
        if "wire-contract" in cfg.checkers and wire_cfg is not None \
                and not is_lint_fixture:
            findings.extend(wire_contract.check(mod, wire_cfg, wire_acc))
        if "lock-order" in cfg.checkers and lock_cfg is not None:
            findings.extend(locks.check(mod, lock_cfg))
        if "rpc-discipline" in cfg.checkers and rpc_cfg is not None \
                and not is_lint_fixture:
            findings.extend(rpc_discipline.check(mod, rpc_cfg))
        if "loop-only" in cfg.checkers:
            if mod.rel in registries:
                findings.extend(loop_only.check_declaring_module(
                    mod, registries[mod.rel]
                ))
            elif registries:
                findings.extend(loop_only.check_instance_hints(
                    mod, hint_map
                ))

    # -- gated registry-wide passes --------------------------------------
    if "env-knob" in cfg.checkers and env_cfg is not None:
        dead = cfg.check_dead_knobs
        if dead is None:
            dead = env_registry_mod is not None
        if dead:
            decl_lines = (
                env_knobs.registry_decl_lines(env_registry_mod)
                if env_registry_mod is not None else {}
            )
            findings.extend(
                env_knobs.check_dead(env_cfg, env_uses, decl_lines)
            )
    if (
        "metrics-registry" in cfg.checkers
        and metrics_cfg is not None
        and metrics_registry_mod is not None
    ):
        findings.extend(metrics.check_dead(
            metrics_cfg, metric_uses,
            metrics.registry_decl_lines(metrics_registry_mod),
        ))
    if (
        "chaos-registry" in cfg.checkers
        and chaos_cfg is not None
        and chaos_registry_mod is not None
    ):
        findings.extend(chaos.check_dead(
            chaos_cfg, chaos_uses,
            chaos.registry_decl_lines(chaos_registry_mod),
        ))
    if (
        "wire-contract" in cfg.checkers
        and wire_cfg is not None
        and wire_registry_mod is not None
    ):
        findings.extend(wire_contract.check_global(
            wire_cfg, wire_acc,
            wire_contract.registry_decl_lines(wire_registry_mod),
        ))
    if (
        "rpc-discipline" in cfg.checkers
        and rpc_cfg is not None
        and rpc_registry_mod is not None
    ):
        findings.extend(rpc_discipline.check_registry(rpc_cfg, cfg.root))

    # -- allowlist -------------------------------------------------------
    if cfg.allowlist_path and os.path.exists(cfg.allowlist_path):
        entries = parse_allowlist(cfg.allowlist_path)
        rel = os.path.relpath(
            os.path.abspath(cfg.allowlist_path), cfg.root
        ).replace(os.sep, "/")
        findings = apply_allowlist(
            findings, entries, rel,
            scanned_rels={m.rel for m in modules},
            active_checkers=set(cfg.checkers) | {"parse", "allowlist"},
        )

    findings.sort(key=lambda f: (f.path, f.line, f.checker))
    return findings

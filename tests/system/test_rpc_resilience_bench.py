"""ISSUE 14 acceptance (bench leg): the `rpc_resilience` phase banks
an attested CPU-proxy record for the substrate's hedged-read A/B —
hash-verified chunk pulls from two loopback holders under the injected
`delay` chaos action — and `validate_bench.py` refuses records whose
hedged p99 isn't measurably below the unhedged one, whose unhedged arm
never ate the injected tail (an A/B that measured nothing), or whose
win/cancel accounting shows the hedges never ran or leaked losers.

Time budget: the phase itself is ~10 s of loopback HTTP (tier-1); the
validator-teeth test is milliseconds.
"""

import importlib.util
import json
import os

import pytest

from areal_tpu.bench import bank

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

pytestmark = pytest.mark.serial


def _load_validator():
    spec = importlib.util.spec_from_file_location(
        "validate_bench", os.path.join(REPO, "scripts", "validate_bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _fake_record():
    """A well-formed rpc_resilience value (what a healthy run banks)."""
    return {
        "n_chunks": 32.0,
        "injected_delay_ms": 350.0,
        "hedge_delay_ms": 50.0,
        "unhedged_p50_ms": 60.0,
        "unhedged_p99_ms": 420.0,
        "hedged_p50_ms": 70.0,
        "hedged_p99_ms": 105.0,
        "hedge_wins": 32.0,
        "hedge_cancelled": 32.0,
        "hedge_failures": 0.0,
    }


def test_validator_teeth_for_rpc_resilience():
    validator = _load_validator()

    def problems(**mut):
        val = {**_fake_record(), **mut}
        rec = {"status": "ok", "pass": "measure", "value": val}
        return validator.validate_phase_value("rpc_resilience", rec)

    assert problems() == []
    # Hedging bought nothing: hedged p99 at/above unhedged.
    assert problems(hedged_p99_ms=500.0)
    # The slow peer never landed: the hedged number proves nothing.
    assert problems(unhedged_p99_ms=100.0)
    # Hedged arm still stuck at the injected tail.
    assert problems(hedged_p99_ms=360.0, unhedged_p99_ms=420.0)
    # Accounting: a low p99 without wins/cancels isn't hedging evidence.
    assert problems(hedge_wins=0.0)
    assert problems(hedge_cancelled=0.0)
    assert problems(hedge_failures=1.0)
    # Schema: every declared key must be present and numeric.
    incomplete = _fake_record()
    del incomplete["hedge_wins"]
    rec = {"status": "ok", "pass": "measure", "value": incomplete}
    assert validator.validate_phase_value("rpc_resilience", rec)


def test_rpc_resilience_banks_and_validates(tmp_path, monkeypatch):
    b = str(tmp_path / "bank")
    monkeypatch.setenv("AREAL_BENCH_BANK", b)
    from areal_tpu.bench.workloads import rpc_resilience_phase

    val = rpc_resilience_phase("measure")
    path = bank.write_record(
        bank.make_record("rpc_resilience", "measure", "ok", value=val), b
    )
    with open(path) as f:
        rec = json.load(f)
    bank.validate_record(rec)
    assert rec["attestation"]["platform"] == "cpu"
    assert rec["attestation"]["driver_verified"] is False

    validator = _load_validator()
    assert validator.validate_phase_value("rpc_resilience", rec) == []
    assert validator.validate_bank_dir(b) == []

"""Elo rating estimator: MLE recovery, degenerate cases, percentile, and
the results-join CLI surface (reference evaluation/cf_elo_caculator.py)."""

import json
import math
import random

import pytest

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))
from evaluation.elo import (
    estimate_rating,
    get_percentile,
    rate_results,
    read_ratings,
    solve_probability,
)


def _simulate(true_rating, difficulties, seed=0):
    rng = random.Random(seed)
    return [
        (d, rng.random() < solve_probability(true_rating, d))
        for d in difficulties
    ]


def test_mle_recovers_true_rating():
    rng = random.Random(1)
    difficulties = [rng.uniform(800, 3000) for _ in range(400)]
    for true in (1200.0, 1900.0, 2600.0):
        outcomes = _simulate(true, difficulties, seed=int(true))
        est = estimate_rating(outcomes)
        assert abs(est - true) < 120, (true, est)


def test_degenerate_outcomes_clamp():
    assert estimate_rating([(1500, True), (2000, True)]) == 4000.0
    assert estimate_rating([(1500, False)]) == 0.0
    with pytest.raises(ValueError):
        estimate_rating([])


def test_monotonic_in_solves():
    diffs = [1000.0, 1500.0, 2000.0, 2500.0]
    r1 = estimate_rating([(d, d <= 1000) for d in diffs])
    r2 = estimate_rating([(d, d <= 2000) for d in diffs])
    assert r2 > r1


def test_percentile_and_ratings_format(tmp_path):
    path = tmp_path / "ratings.json"
    path.write_text(json.dumps({"1000": 2, "1500": 2, "2000": 1}))
    ratings = read_ratings(str(path))
    assert ratings == [1000.0, 1000.0, 1500.0, 1500.0, 2000.0]
    assert get_percentile(1600, ratings) == 80.0
    assert get_percentile(500, ratings) == 0.0


def test_rate_results_join():
    results = {
        "details": [
            {"query_id": "a", "correct": True},
            {"query_id": "b", "correct": False},
            {"query_id": "missing", "correct": True},
        ]
    }
    difficulties = {"a": 1200.0, "b": 2400.0}
    out = rate_results(results, difficulties, sorted_ratings=[1000.0, 2000.0])
    assert out["n_problems"] == 2
    assert out["n_skipped_no_difficulty"] == 1
    assert out["n_solved"] == 1
    assert 1200.0 < out["rating"] < 2400.0
    assert "percentile" in out
    assert math.isfinite(out["rating"])

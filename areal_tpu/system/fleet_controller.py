"""Elastic fleet control plane: join/leave, autoscaling, manager HA.

The three legs of ROADMAP item 1 (docs/fault_tolerance.md "Fleet
elasticity + manager HA"):

- **Runtime join/leave.** The gserver manager no longer treats the
  fleet as fixed at launch: a freshly spawned ``GenerationServer``
  registers through the normal name_resolve/heartbeat path, the
  manager ADOPTS it (``_admit_server``), bootstraps its weight shard
  from *peers* over the PR 5/8 weight plane (origin last resort, never
  NFS), and only then admits it to routing. Leave is drain-then-exit:
  the server quiesces admission, finishes in-flight work, migrates its
  parked KV prefixes to peers over the PR 7/11 KV wire, and departs
  with a graceful heartbeat-stop marker the manager folds into a clean
  ``_forget_server``.

- **Watermark autoscaling.** :class:`WatermarkAutoscaler` turns the
  same queued-token / free-page signals the PR 7 re-role sizer polls
  into scale-out/in decisions (sustained-watermark + cooldown + pool
  floors/ceilings), actuated through a pluggable :class:`Launcher`
  (:class:`SubprocessLauncher` locally; production substitutes its own
  scheduler client — the interface is the contract).

- **Manager HA.** :class:`ManagerLease` persists the only state a
  manager restart cannot rebuild — a tiny epoch + weight-version
  record in name_resolve. Everything else (membership, roles, shards,
  shed totals, per-server versions) is rebuilt from heartbeats and
  ``/metrics`` by :func:`rebuild_fleet_state`; the affinity map is
  best-effort lost (the global prefix index re-feeds from the next
  ``/kv/index`` poll, so returning sessions still find their KV).
  A successor takes over by waiting out the lease and writing the next
  epoch; ``partial_rollout`` clients ride the outage with rediscovery
  + jittered backoff instead of failing rollouts.

Everything here runs on the manager's worker POLL thread (or at
configure time) — never on its HTTP event loop: lease reads/writes are
name_resolve file I/O and :func:`fetch_metrics` is a blocking HTTP GET
(the areal-lint blocking-async contract).
"""

from __future__ import annotations

import dataclasses
import json
import subprocess
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from areal_tpu.base import env_registry, health, logging, name_resolve, names, rpc
from areal_tpu.base import metrics_registry as mreg
from areal_tpu.base.wire_schemas import FLEET_LEASE_V1

logger = logging.getLogger("fleet_controller")

# Machine-checked thread contract (areal_tpu/lint, checker `loop-only`;
# docs/static_analysis.md): the autoscaler's debounce/cooldown counters
# are owned by the manager's poll thread — `observe` is the only entry
# point and has no locks by design. Anything else (the /status surface)
# reads the manager's own lock-guarded scale log, never these.
AREAL_LINT_LOOP_ONLY = {
    "WatermarkAutoscaler": {
        "roots": ["observe"],
        "attrs": ["_over_polls", "_under_polls", "_cooldown_until"],
        "init_ok": ["__init__"],
        "instance_hints": ["autoscaler"],
    },
}


def lease_ttl() -> float:
    """Manager-lease TTL seconds (AREAL_FLEET_LEASE_TTL overrides; the
    default tracks the health-registry TTL so one knob tunes both
    failure-detection horizons in tests and chaos drills)."""
    v = env_registry.get_float("AREAL_FLEET_LEASE_TTL")
    return v if v is not None else health.default_ttl()


@dataclasses.dataclass
class LeaseRecord:
    epoch: int
    addr: str
    weight_version: int
    ts: float
    ttl: float

    def expired(self, now: Optional[float] = None) -> bool:
        """Stale by more than STALE_FACTOR TTLs — same slack the health
        registry gives a missed beat (one hiccup must not trigger a
        takeover of a live manager)."""
        now = time.time() if now is None else now
        return now - self.ts > self.ttl * health.STALE_FACTOR


class ManagerLease:
    """The manager's tiny durable record: epoch + weight version.

    This is deliberately ALL the state a manager persists. The epoch
    fences generations (a successor writes epoch+1, so two managers can
    never both believe they own the fleet after a partition heals — the
    stale one sees a higher epoch on its next renew and stands down);
    ``weight_version`` is the one routing input that cannot be rebuilt
    from the fleet itself race-free (a successor inheriting version 0
    would re-fanout and re-sync healthy servers for nothing). Records
    are written with ``delete_on_exit=False``: the lease must survive
    the manager's death — its staleness IS the takeover signal.
    """

    def __init__(self, experiment_name: str, trial_name: str,
                 ttl: Optional[float] = None):
        self._key = names.fleet_manager_lease(experiment_name, trial_name)
        self.ttl = ttl if ttl is not None else lease_ttl()
        self._last_renew = 0.0
        self.epoch = 0
        self.addr = ""

    def read(self) -> Optional[LeaseRecord]:
        try:
            raw = json.loads(name_resolve.get(self._key))
        except (name_resolve.NameEntryNotFoundError, ValueError):
            return None
        if raw.get("schema") != FLEET_LEASE_V1:
            return None
        try:
            return LeaseRecord(
                epoch=int(raw["epoch"]),
                addr=str(raw.get("addr", "")),
                weight_version=int(raw.get("weight_version", 0)),
                ts=float(raw.get("ts", 0.0)),
                ttl=float(raw.get("ttl", self.ttl)),
            )
        except (KeyError, TypeError, ValueError):
            return None

    def _write(self, epoch: int, addr: str, weight_version: int):
        record = {
            "schema": FLEET_LEASE_V1,
            "epoch": int(epoch),
            "addr": addr,
            "weight_version": int(weight_version),
            "ts": time.time(),
            "ttl": self.ttl,
        }
        name_resolve.add(
            self._key, json.dumps(record, separators=(",", ":")),
            delete_on_exit=False, replace=True,
        )
        self.epoch, self.addr = int(epoch), addr
        self._last_renew = time.monotonic()

    def wait_expired(self, timeout: float = 300.0) -> Optional[LeaseRecord]:
        """Block until the current holder's lease is expired (or there
        is none); returns the last-seen prior record. A warm standby
        parks here and takes over the moment the holder stops
        renewing."""
        deadline = time.monotonic() + timeout
        prior = self.read()
        while prior is not None and not prior.expired():
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"manager lease still held by {prior.addr} "
                    f"(epoch {prior.epoch}) after {timeout:.0f}s"
                )
            time.sleep(min(0.2, self.ttl / 4))
            prior = self.read()
        return prior

    def take(self, addr: str, weight_version: int,
             prior: Optional[LeaseRecord] = None) -> int:
        """Write the next epoch and become the holder; returns the new
        epoch. ``prior`` is the record ``wait_expired`` returned (None
        on first boot)."""
        epoch = (prior.epoch if prior is not None else 0) + 1
        self._write(epoch, addr, weight_version)
        logger.info(
            f"manager lease epoch {epoch} taken by {addr} "
            f"(weight_version={weight_version})"
        )
        return epoch

    def renew(self, weight_version: int, force: bool = False) -> bool:
        """Rate-limited (ttl/3) renewal from the holder's poll loop.
        Returns False — and does NOT write — when a higher epoch has
        appeared: the caller has been superseded and must stand down
        instead of dueling the successor's routing state."""
        if not force and time.monotonic() - self._last_renew < self.ttl / 3:
            return True
        cur = self.read()
        if cur is not None and (
            cur.epoch > self.epoch
            or (cur.epoch == self.epoch and cur.addr != self.addr)
        ):
            # Higher epoch: a successor fenced us. SAME epoch but a
            # different address: two racing takeovers wrote the same
            # epoch (take() is last-writer-wins, not compare-and-swap)
            # — the one whose write lost the race stands down here, so
            # an equal-epoch duel resolves within one renew period.
            logger.warning(
                f"manager lease epoch {cur.epoch} (holder {cur.addr}) "
                f"superseded ours (epoch {self.epoch}, {self.addr}); "
                f"standing down"
            )
            return False
        try:
            self._write(self.epoch, self.addr, weight_version)
        except Exception:
            # A flaky KV write must not kill the manager it protects;
            # the next poll lap retries (the slack is STALE_FACTOR TTLs).
            logger.warning("manager lease renew failed", exc_info=True)
        return True


# ----------------------------------------------------------------------
# State rebuild (manager HA)
# ----------------------------------------------------------------------

@dataclasses.dataclass
class FleetState:
    """What a successor manager rebuilds from heartbeats + /metrics —
    everything /status needs except the affinity map (best-effort; the
    global prefix index re-feeds from the next /kv/index poll) and
    in-flight load estimates (refreshed by the first metrics poll)."""

    urls: List[str] = dataclasses.field(default_factory=list)
    member_urls: Dict[str, str] = dataclasses.field(default_factory=dict)
    roles: Dict[str, str] = dataclasses.field(default_factory=dict)
    # url -> model_id ("" = undeclared legacy server): a successor of a
    # multi-model manager must rebuild the per-model pool map too, or
    # its first routing decisions could cross model boundaries.
    model_ids: Dict[str, str] = dataclasses.field(default_factory=dict)
    shards: Dict[str, Optional[Tuple[int, int]]] = dataclasses.field(
        default_factory=dict
    )
    elastic: Dict[str, bool] = dataclasses.field(default_factory=dict)
    versions: Dict[str, int] = dataclasses.field(default_factory=dict)
    shed_totals: Dict[str, float] = dataclasses.field(default_factory=dict)
    draining: List[str] = dataclasses.field(default_factory=list)
    server_indices: Dict[str, int] = dataclasses.field(default_factory=dict)


def parse_metrics(text: str) -> Dict[str, Any]:
    """One server's /metrics text -> {line_key: float-or-str} (the
    ProcessFleet/e2e parsing shape, shared here for the rebuild)."""
    out: Dict[str, Any] = {}
    for line in text.splitlines():
        parts = line.split()
        if len(parts) == 2:
            try:
                out[parts[0]] = float(parts[1])
            except ValueError:
                out[parts[0]] = parts[1]
    return out


def fetch_metrics(url: str, timeout: float = 5.0) -> Dict[str, Any]:
    """Blocking GET {url}/metrics -> parsed dict ({} when unreachable).
    Poll-thread / configure-time only (never the HTTP event loop).
    Single-attempt on purpose — the poll loop IS the retry — but routed
    through base/rpc.py so the timeout is the declared budget, not a
    naked literal."""
    try:
        body = rpc.get_bytes_sync(
            url + "/metrics",
            policy=rpc.default_policy(attempts=1, attempt_timeout_s=timeout),
            deadline=rpc.Deadline.after(timeout),
            what="fleet metrics",
        )
        return parse_metrics(body.decode())
    except Exception:
        return {}


def _shard_of(record_shard, metrics_shard) -> Optional[Tuple[int, int]]:
    if record_shard and len(record_shard) == 2:
        return (int(record_shard[0]), int(record_shard[1]))
    if isinstance(metrics_shard, str) and "/" in metrics_shard:
        r_s, d_s = metrics_shard.split("/", 1)
        try:
            return (int(r_s), int(d_s))
        except ValueError:
            return None
    return None


def rebuild_fleet_state(
    heartbeats: Dict[str, Dict],
    metrics: Optional[Dict[str, Dict[str, Any]]] = None,
) -> FleetState:
    """Rebuild the routing-table view from a health-registry snapshot
    (member -> record) plus optional per-url parsed /metrics.

    Heartbeat payloads are authoritative for membership and identity
    (url, server_index, weight shard, draining flag) — a server that
    beats IS in the fleet; /metrics refines live surfaces (role as the
    server sees it, weight version, elastic eligibility, shed totals).
    Pure function: the satellite-3 unit test drives it over fakes and
    diffs the result against a pre-kill manager's /status."""
    metrics = metrics or {}
    st = FleetState()
    for member, record in sorted(heartbeats.items()):
        url = record.get("url")
        if not url or record.get("stopped"):
            continue
        m = metrics.get(url) or {}
        st.urls.append(url)
        st.member_urls[member] = url
        # Registry constants, not literals: a renamed /metrics line is
        # a lint failure here, not a takeover that rebuilds every
        # surface as its zero-value default.
        role = m.get(mreg.ROLE) or record.get("role") or "unified"
        st.roles[url] = str(role)
        mid = record.get("model_id") or m.get(mreg.MODEL_ID) or ""
        st.model_ids[url] = "" if mid in ("-", None) else str(mid)
        st.shards[url] = _shard_of(
            record.get("weight_shard"), m.get(mreg.WEIGHT_SHARD)
        )
        st.elastic[url] = bool(float(m.get(mreg.ELASTIC) or 0.0) > 0.5)
        st.versions[url] = int(float(m.get(mreg.WEIGHT_VERSION) or 0.0))
        st.shed_totals[url] = float(m.get(mreg.LOAD_SHED_TOTAL) or 0.0)
        if record.get("draining") or float(
            m.get(mreg.DRAINING) or 0.0
        ) > 0.5:
            st.draining.append(url)
        if record.get("server_index") is not None:
            st.server_indices[url] = int(record["server_index"])
    st.urls.sort()
    return st


# ----------------------------------------------------------------------
# Watermark autoscaling
# ----------------------------------------------------------------------

@dataclasses.dataclass
class AutoscalePolicy:
    """Scale-out/in thresholds over the fleet's queued-token and
    free-page watermarks (the PR 7 re-role sizer's signals, one level
    up: the sizer moves servers BETWEEN pools, this adds/removes
    servers)."""

    # Fleet-average queued prompt tokens per routable server at or
    # above which the fleet grows (sustained, see sustain_polls).
    scale_out_queued_tokens: int = 4096
    # ... at or below which the fleet shrinks (only while the decode
    # free-page fraction is comfortable — draining a server under page
    # pressure would amplify it).
    scale_in_queued_tokens: int = 64
    scale_free_page_min_frac: float = 0.5
    pool_min_servers: int = 1
    pool_max_servers: int = 8
    cooldown_s: float = 15.0
    # Consecutive over/under-watermark observations before acting — one
    # bursty poll must not launch a server.
    sustain_polls: int = 2


class WatermarkAutoscaler:
    """Debounced watermark policy. ``observe`` is called once per
    metrics poll from the manager's poll thread and returns "out",
    "in", or None; actuation (launcher / drain) belongs to the caller,
    which reports back via the decision's side effect on the next
    observation (n_pending / n_routable)."""

    def __init__(self, policy: AutoscalePolicy,
                 clock: Callable[[], float] = time.monotonic):
        self.policy = policy
        self._clock = clock
        self._over_polls = 0
        self._under_polls = 0
        self._cooldown_until = 0.0

    def observe(
        self,
        n_routable: int,
        n_pending: int,
        queued_tokens_total: float,
        free_page_frac: float,
    ) -> Optional[str]:
        """One poll's decision. ``n_routable`` = healthy non-draining
        servers; ``n_pending`` = launches in flight + joiners mid-
        bootstrap (they count against pool_max so a slow join can't
        trigger a launch storm); ``free_page_frac`` = fleet free/total
        KV pages (1.0 when unreported)."""
        p = self.policy
        if n_routable <= 0:
            # An unroutable fleet is an availability incident, not
            # load: launching into it is right ONLY while nothing is
            # already on its way — otherwise every cooldown period
            # would add another server to a blip that resolves itself.
            self._over_polls = self._over_polls + 1 if n_pending == 0 else 0
            self._under_polls = 0
        else:
            avg_q = queued_tokens_total / n_routable
            if avg_q >= p.scale_out_queued_tokens:
                self._over_polls += 1
                self._under_polls = 0
            elif (
                avg_q <= p.scale_in_queued_tokens
                and free_page_frac >= p.scale_free_page_min_frac
            ):
                self._under_polls += 1
                self._over_polls = 0
            else:
                self._over_polls = 0
                self._under_polls = 0
        now = self._clock()
        if now < self._cooldown_until:
            return None
        if (
            self._over_polls >= p.sustain_polls
            and n_routable + n_pending < p.pool_max_servers
        ):
            self._over_polls = 0
            self._cooldown_until = now + p.cooldown_s
            return "out"
        if (
            self._under_polls >= p.sustain_polls
            and n_routable > p.pool_min_servers
            and n_pending == 0
        ):
            self._under_polls = 0
            self._cooldown_until = now + p.cooldown_s
            return "in"
        return None


# ----------------------------------------------------------------------
# Launchers
# ----------------------------------------------------------------------

class Launcher:
    """Actuation interface for scale-out. Production deployments plug
    their scheduler here (k8s Job, slurm, GKE instance group); the
    contract is just: start a generation server that will register
    itself with ``server_index`` through the normal discovery path.
    The manager only ever calls ``launch`` and ``reap`` from its poll
    thread."""

    def launch(self, server_index: int) -> Any:
        raise NotImplementedError

    def stop(self, handle: Any) -> None:  # best-effort; drain is the
        raise NotImplementedError         # graceful path

    def reap(self) -> None:
        """Collect exited children (avoid zombies); optional."""


class SubprocessLauncher(Launcher):
    """Local actuation: ``spawn_fn(server_index) -> subprocess.Popen``.
    The bench harness and the e2e hand in their child template; the
    launcher only owns handle bookkeeping."""

    def __init__(self, spawn_fn: Callable[[int], "subprocess.Popen"]):
        self._spawn_fn = spawn_fn
        self._lock = threading.Lock()
        self.procs: List["subprocess.Popen"] = []

    def launch(self, server_index: int) -> "subprocess.Popen":
        p = self._spawn_fn(server_index)
        with self._lock:
            self.procs.append(p)
        logger.info(
            f"launched generation server index {server_index} (pid {p.pid})"
        )
        return p

    def stop(self, handle: "subprocess.Popen") -> None:
        try:
            handle.terminate()
        except Exception:
            pass

    def reap(self) -> None:
        with self._lock:
            for p in self.procs:
                p.poll()

    def close(self, timeout: float = 15.0) -> None:
        with self._lock:
            procs = list(self.procs)
        for p in procs:
            self.stop(p)
        for p in procs:
            try:
                p.wait(timeout=timeout)
            except Exception:
                try:
                    p.kill()
                except Exception:
                    pass

"""Math/code RL prompt dataset with curriculum filtering
(reference impl/dataset/math_code_dataset.py).

jsonl rows need "prompt", "query_id", "task" in {math, stem, code} (missing
task defaults to math); math rows carry "solutions" (list of reference
answers), code rows carry "input_output" (JSON testcases). Rows failing
validation are skipped with a warning, matching the reference's tolerance.

Produces `packed_prompts` + per-sample `task_ids` (index into
data_api.RL_TASKS) and optional `base_scores`. `filter(eval_scores)`
implements score-threshold curriculum dropping (reference
math_code_dataset.py:175-202): at most `max_filter_percentage` of active
prompts with scores above `filter_threshold` are removed per call, highest
scores first.
"""

from __future__ import annotations

import json
from collections import defaultdict
from typing import Callable, Dict, Hashable, List, Optional, Tuple

import numpy as np

from areal_tpu.api import data_api
from areal_tpu.base import logging

logger = logging.getLogger("math_code_dataset")


def _validate_math(d: Dict) -> Dict:
    assert d["task"] in ("math", "stem")
    d["query_id"] = str(d["query_id"])
    assert isinstance(d["prompt"], str)
    assert isinstance(d["solutions"], list)
    assert all(isinstance(s, str) for s in d["solutions"])
    return d


def _validate_code(d: Dict) -> Dict:
    assert d["task"] == "code"
    d["query_id"] = str(d["query_id"])
    d.setdefault("problem_id", d["query_id"])
    assert isinstance(d["prompt"], str)
    io = json.loads(d["input_output"]) if isinstance(d["input_output"], str) else d["input_output"]
    assert len(io["inputs"]) == len(io["outputs"])
    return d


def load_metadata(path: str) -> Tuple[Dict[str, Dict], Dict[str, int]]:
    """id->row mapping for reward verification, with per-task counts."""
    assert str(path).endswith(".jsonl"), path
    with open(path) as f:
        rows = [json.loads(line) for line in f if line.strip()]
    id2info: Dict[str, Dict] = {}
    task_cnt: Dict[str, int] = defaultdict(int)
    omit = defaultdict(int)
    for d in rows:
        d.setdefault("task", "math")
        try:
            d = _validate_code(d) if d["task"] == "code" else _validate_math(d)
        except Exception:
            omit[d["task"]] += 1
            continue
        id2info[d["query_id"]] = d
        task_cnt[d["task"]] += 1
    if omit:
        logger.warning(f"math_code dataset: ignored invalid rows {dict(omit)}")
    return id2info, dict(task_cnt)


class MATHCodePromptDataset:
    def __init__(
        self,
        util: data_api.DatasetUtility,
        max_length: Optional[int] = None,
        dataset_path: Optional[str] = None,
        dataset_builder: Optional[Callable[[], List[Dict]]] = None,
        filter_threshold: float = 1e4,
        max_filter_percentage: float = 0.0,
    ):
        self.util = util
        # Read + validate the FULL dataset once, then split: every rank must
        # agree on the kept row set and on whether base_scores exist (a
        # per-slice decision would give ranks inconsistent key sets).
        if dataset_path is not None:
            assert str(dataset_path).endswith(".jsonl"), dataset_path
            with open(dataset_path) as f:
                rows = [json.loads(line) for line in f if line.strip()]
        else:
            rows = dataset_builder()
        valid = []
        omit: Dict[str, int] = defaultdict(int)
        for d in rows:
            d.setdefault("task", "math")
            try:
                valid.append(
                    _validate_code(d) if d["task"] == "code" else _validate_math(d)
                )
            except Exception:
                omit[d["task"]] += 1
        if omit:
            logger.warning(f"math_code dataset: ignored invalid rows {dict(omit)}")
        has_base_scores = bool(valid) and all("scores" in d for d in valid)
        data = data_api.load_shuffle_split_dataset(util, None, lambda: valid)

        enc = util.tokenizer(
            [x["prompt"] for x in data],
            truncation=False,
            padding=False,
            return_attention_mask=False,
        )
        keep = [
            i
            for i, ids in enumerate(enc["input_ids"])
            if max_length is None or len(ids) <= max_length
        ]
        self.prompts: List[List[int]] = [enc["input_ids"][i] for i in keep]
        self.prompt_lengths = [len(p) for p in self.prompts]
        # Unique per-(row, dp_rank) ids, as in the reference (:138-140).
        self.ids = [
            f"{data[i]['query_id']}@idx:{i}-{util.dp_rank}" for i in keep
        ]
        self.task_ids = [data_api.RL_TASKS.index(data[i].get("task", "math")) for i in keep]
        self.tasks = [data[i]["task"] for i in keep]
        self.query_ids = [data[i]["query_id"] for i in keep]
        # What the reward verifier needs per prompt: reference answers for
        # math, testcases for code (reference keeps a global id2info instead;
        # carrying it in sample metadata keeps the reward worker stateless).
        self.answer_infos = [
            data[i]["input_output"] if data[i]["task"] == "code" else data[i]["solutions"]
            for i in keep
        ]
        self.base_scores = (
            [float(np.mean(data[i]["scores"])) for i in keep]
            if has_base_scores
            else None
        )
        self.active_indices = list(range(len(self.prompts)))
        self.filter_threshold = filter_threshold
        self.max_filter_percentage = max_filter_percentage
        logger.info(
            f"MATHCodePromptDataset: {len(self.prompts)} prompts (dp={util.dp_rank})"
        )

    def __len__(self):
        return len(self.active_indices)

    def __getitem__(self, idx: int) -> data_api.SequenceSample:
        idx = self.active_indices[idx]
        d = dict(
            packed_prompts=np.asarray(self.prompts[idx], dtype=np.int32),
            task_ids=np.asarray([self.task_ids[idx]], dtype=np.int64),
        )
        if self.base_scores is not None:
            d["base_scores"] = np.asarray([self.base_scores[idx]], dtype=np.float32)
        return data_api.SequenceSample.from_default(
            ids=[self.ids[idx]],
            seqlens=[self.prompt_lengths[idx]],
            data=d,
            metadata=dict(
                tasks=[self.tasks[idx]],
                solutions=[self.answer_infos[idx]],
                query_ids=[self.query_ids[idx]],
            ),
        )

    def filter(self, eval_scores: Dict[Hashable, float]):
        """Curriculum filter: drop up to max_filter_percentage of active
        prompts whose eval score exceeds filter_threshold (highest first)."""
        removable = {}
        for pos, idx in enumerate(self.active_indices):
            score = eval_scores.get(self.ids[idx])
            if score is not None and score > self.filter_threshold:
                removable[pos] = score
        n = int(len(self.active_indices) * self.max_filter_percentage)
        to_remove = sorted(removable, key=removable.__getitem__, reverse=True)[:n]
        for pos in sorted(to_remove, reverse=True):
            self.active_indices.pop(pos)
        logger.info(
            f"math_code filter: removed {len(to_remove)}, "
            f"{len(self.active_indices)} remain"
        )


data_api.register_dataset("math_code_prompt", MATHCodePromptDataset)

"""Single registry of every ``AREAL_*`` environment knob.

Before this module existed the tree held ~60 ad-hoc ``os.environ``
reads with per-call-site defaults — the drift class that forced PR 1 to
bolt construction-time snapshotting onto ``AREAL_CE_CHUNK`` /
``AREAL_SPLASH_*`` after two call sites disagreed about a default.
Every knob is now declared ONCE here (name, type, default, doc,
snapshot-at-construction flag) and read through the typed accessors
below; the ``env-knob`` checker in ``areal_tpu/lint`` flags any raw
``os.environ``/``getenv`` read of an undeclared ``AREAL_*`` name, any
raw read of a *declared* name outside this module (use an accessor),
and any registry entry nothing reads (dead knob).

``docs/env_vars.md`` is GENERATED from this registry
(``python scripts/areal_lint.py --emit-env-docs docs/env_vars.md``) and
drift-gated in tier-1, so the doc can't fork from the code.

Accessor semantics (uniform, unlike the historical call sites):

- unset **or empty-string** values fall back to the declared default
  (historically ``os.environ.get(k, d)`` sites crashed on ``k=""``
  while ``os.environ.get(k) or d`` sites silently defaulted);
- booleans: ``"" / "0" / "false" / "no" / "off"`` (case-insensitive)
  are False, anything else set is True (historically
  ``AREAL_WEIGHT_PLANE=0`` meant *enabled* because the site tested
  plain string truthiness);
- a knob whose declared default is ``None`` returns ``None`` when
  unset (the "optional override" pattern).

This module must stay stdlib-only: it is imported by
``areal_tpu/base/logging.py`` and by the no-jax lint gate.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, List, Optional

_FALSEY = ("", "0", "false", "no", "off")


@dataclasses.dataclass(frozen=True)
class Knob:
    name: str
    kind: str  # "str" | "int" | "float" | "bool"
    default: Any  # typed default, or None for "optional override" knobs
    doc: str
    # True: read once at construction/init and pinned for the object's
    # lifetime — mid-run env edits must NOT change behavior (a retrace
    # or retry re-reading a changed value was the PR 1 drift bug).
    snapshot: bool = False


def _k(name: str, kind: str, default: Any, doc: str, *,
       snapshot: bool = False) -> Knob:
    return Knob(name=name, kind=kind, default=default, doc=doc,
                snapshot=snapshot)


_KNOBS: List[Knob] = [
    # -- engine / serving ------------------------------------------------
    _k("AREAL_KV_CACHE_DTYPE", "str", None,
       "KV pool precision default when the engine ctor passes None: "
       "'model' or 'int8' (paged.py int8 KV pools). A/B hook so bench "
       "runs need no plumbing.", snapshot=True),
    _k("AREAL_SPEC_DRAFT", "int", 0,
       "N-gram speculative-decoding draft length default when the "
       "engine ctor passes 0 (engine/spec_decode.py). 0 disables.",
       snapshot=True),
    _k("AREAL_SPEC_WINDOW", "int", None,
       "Backward search window (tokens) for the speculative n-gram "
       "lookup; unset = 1024, 0 = unbounded full-history scan.",
       snapshot=True),
    _k("AREAL_DECODE_WEIGHT_DTYPE", "str", None,
       "Decode-weight precision default when the engine ctor passes "
       "None: 'model' or 'int8' (W8A16, ops/wquant.py).", snapshot=True),
    _k("AREAL_CHUNK_SMEM_BUDGET", "int", 512 * 1024,
       "SMEM byte budget the chunked-prefill kernel sizes its blocks "
       "against (engine/paged.py).", snapshot=True),
    # -- tiered KV plane (engine/kv_tier.py, docs/serving.md) ------------
    _k("AREAL_KV_TIER_BYTES", "int", 0,
       "Host-RAM KV tier capacity in bytes when the engine ctor passes "
       "None: prefix-cache evictions SPILL here (handoff wire format) "
       "instead of being freed, and a returning session restores the "
       "prefix instead of re-prefilling. 0 disables the tier.",
       snapshot=True),
    _k("AREAL_KV_TIER_DISK_DIR", "str", None,
       "Optional local-disk second KV tier: host-tier LRU evictions "
       "demote into this directory instead of being dropped (read back "
       "with per-chunk hash verification). Unset = no disk tier.",
       snapshot=True),
    _k("AREAL_KV_TIER_DISK_BYTES", "int", 1 << 30,
       "Capacity of the local-disk KV tier (AREAL_KV_TIER_DISK_DIR); "
       "LRU entries beyond it are dropped for good.", snapshot=True),
    _k("AREAL_KV_SPILL_DTYPE", "str", None,
       "KV spill wire precision when the engine ctor passes None: "
       "'int8' quantizes a FLOAT pool's prefixes on the spill wire "
       "(quantize_kv — halves tier bytes); 'fp8' uses the e4m3 wire "
       "(kv_handoff.quantize_kv_fp8 — same 1-byte footprint, floating "
       "mantissa so small-magnitude KV keeps relative precision); "
       "int8 pools always spill their (data, scales) form unchanged. "
       "None/'model' ships the pool's own precision.", snapshot=True),
    _k("AREAL_KV_INDEX_SIZE", "int", 65536,
       "LRU capacity of the gserver manager's global prefix index "
       "(qid -> holder + tier, fed from each server's /kv/index) when "
       "GserverManagerConfig.kv_index_size is unset."),
    _k("AREAL_CKPT_BACKEND", "str", "pickle",
       "Checkpoint storage backend when the API caller passes none: "
       "'pickle' or 'orbax' (engine/checkpoint.py)."),
    _k("AREAL_CKPT_ASYNC", "bool", False,
       "Route pickle-backend engine checkpoints through the background "
       "writer (engine/checkpoint.py): the step loop pays only a "
       "reference-snapshot stall while device->host fetch + fsync + "
       "rename run off-thread. Orbax saves stay synchronous "
       "(collectives are not thread-safe off the main loop)."),
    _k("AREAL_WAL", "bool", True,
       "Arm the rollout-buffer write-ahead log + exactly-once sample "
       "ledger (system/wal.py, system/stream_dataset.py, "
       "system/push_pull_stream.py): accepted samples journal to disk "
       "before acking the pusher, restarts replay unconsumed entries. "
       "False restores the fire-and-forget pre-WAL wire."),
    _k("AREAL_WAL_FSYNC_MS", "float", 50.0,
       "Max milliseconds an appended WAL record may sit before the "
       "batched fsync (and its deferred pusher ack) flushes it "
       "(system/wal.py). 0 = fsync every append."),
    _k("AREAL_WAL_ACK_TIMEOUT_S", "float", 5.0,
       "Seconds a pushed sample may sit unacked before the pusher "
       "redelivers it (system/push_pull_stream.py); the puller-side "
       "ledger makes redelivery idempotent."),
    _k("AREAL_WAL_REDELIVER_MAX", "int", 0,
       "Redelivery attempts per unacked sample before the pusher drops "
       "it and counts areal:train_samples_lost_total "
       "(system/push_pull_stream.py); 0 = retry forever (exactly-once "
       "mode: nothing is ever dropped)."),
    _k("AREAL_PREFETCH_DEPTH", "int", None,
       "Host-prefetcher queue depth override for the train engine "
       "(engine/jax_engine.py); unset = config/ctor default.",
       snapshot=True),
    _k("AREAL_DECODE_RESIDENT", "bool", True,
       "Device-resident decode dispatch (engine/serving.py): page-table "
       "edits land as donated per-slot row scatters and chunk-prefill "
       "control crosses as ONE fused array, so only admission/eviction "
       "DELTAS pay H2D between decode blocks. False restores the "
       "legacy full-table restage + per-scalar staging (the "
       "kernel_micro_decode_state A/B arm; greedy-token parity between "
       "the modes is pinned in tests).", snapshot=True),
    # -- base ------------------------------------------------------------
    _k("AREAL_FILEROOT", "str", None,
       "Filesystem root for logs/checkpoints/realloc params; unset = "
       "/tmp/areal_tpu/$USER. Resolved at call time, not import time "
       "(base/constants.py: workers import before the controller env "
       "lands)."),
    _k("AREAL_LOG_LEVEL", "str", "INFO",
       "Root log level for areal_tpu loggers (base/logging.py)."),
    _k("AREAL_FAULTS", "str", "",
       "Deterministic chaos-injection spec, e.g. "
       "'gserver.weight_fetch@0.5:seed=7' (base/fault_injection.py); "
       "empty = no faults."),
    _k("AREAL_HEALTH_TTL", "float", 10.0,
       "Default lease TTL seconds for the health registry "
       "(base/health.py); per-role overrides via worker config."),
    _k("AREAL_FLEET_LEASE_TTL", "float", None,
       "Gserver-manager HA lease TTL seconds "
       "(system/fleet_controller.py): a successor takes over once the "
       "record is stale by 3x this. Unset = AREAL_HEALTH_TTL, so one "
       "knob tunes both failure-detection horizons."),
    _k("AREAL_NAME_RESOLVE_ROOT", "str", "/tmp/areal_tpu/name_resolve",
       "Root directory for the filesystem name-resolve backend "
       "(base/name_resolve.py)."),
    _k("AREAL_TPU_MEMORY_KILL_THRESHOLD", "float", None,
       "Host-memory fraction above which the monitor kills the worker "
       "(base/monitor.py); unset = disabled."),
    # -- tracing: TWO distinct trace trees (near-collision, kept) --------
    _k("AREAL_DUMP_TRACE", "bool", False,
       "Arm jax.profiler XLA/device trace dumps "
       "(utils/profiling.py). Distinct from AREAL_RL_TRACE, which "
       "records request-scoped RL spans."),
    _k("AREAL_TRACE_DIR", "str", "/tmp/areal_tpu/traces",
       "Output root for AREAL_DUMP_TRACE jax-profiler dumps. NOT the "
       "RL span dir — that is AREAL_RL_TRACE_DIR. The names nearly "
       "collide; both are load-bearing and documented here on purpose "
       "(lint env-knob checker would flag a third variant)."),
    _k("AREAL_TRACE_STEPS", "str", "",
       "Comma/range list of train steps to profile under "
       "AREAL_DUMP_TRACE (utils/profiling.py); empty = all."),
    _k("AREAL_RL_TRACE", "bool", False,
       "Arm the request-scoped RL span recorder (base/tracing.py; "
       "merge tool: scripts/merge_rl_trace.py)."),
    _k("AREAL_RL_TRACE_DIR", "str", None,
       "Output dir for RL span shards; unset = "
       "/tmp/areal_tpu/rl_trace[/<scope>]. See AREAL_TRACE_DIR note."),
    _k("AREAL_RL_TRACE_RING", "int", 65536,
       "Span ring-buffer capacity per worker before drops "
       "(base/tracing.py).", snapshot=True),
    # -- ops -------------------------------------------------------------
    _k("AREAL_CE_CHUNK", "int", None,
       "Cross-entropy vocab-chunk size override (ops/loss.py); unset = "
       "heuristic. Snapshotted at first use per jit trace.",
       snapshot=True),
    _k("AREAL_SPLASH_BQ", "int", 512,
       "Splash-attention query block target (ops/attention.py); "
       "pinned at engine construction.", snapshot=True),
    _k("AREAL_SPLASH_BKV", "int", 1024,
       "Splash-attention KV block target.", snapshot=True),
    _k("AREAL_SPLASH_BKVC", "int", 512,
       "Splash-attention KV-compute block target.", snapshot=True),
    _k("AREAL_GAE_IMPL", "str", "auto",
       "Trainer GAE implementation (ops/gae.packed_gae): 'auto' "
       "(associative scan), 'scan' (the serial lax.scan oracle), "
       "'assoc', or 'pallas' (blocked Pallas scan kernel, shape-gated; "
       "opt-in until kernel_micro_gae banks device crossover "
       "evidence). Pinned when the PPO prep program is first traced.",
       snapshot=True),
    # -- MoE dispatch (models/moe.py, engine/jax_engine.py) --------------
    _k("AREAL_MOE_DISPATCH", "str", None,
       "Training-time MoE dispatch override ('capacity' or 'dropless'); "
       "unset = the model config's moe.dispatch. Applied at engine "
       "construction (engine/jax_engine.py), so it participates in the "
       "jit cache key via the model config.", snapshot=True),
    _k("AREAL_MOE_DECODE_DISPATCH", "str", "dropless",
       "Decode-time MoE dispatch (engine/paged.py): 'dropless' (default "
       "— decode token counts are tiny, so capacity buckets quantize "
       "badly), 'capacity', or 'model' to follow the model config.",
       snapshot=True),
    _k("AREAL_MOE_DECODE_CAPACITY", "float", None,
       "Decode-time capacity_factor override used when the decode "
       "dispatch resolves to 'capacity'; unset = the model config's "
       "moe.capacity_factor.", snapshot=True),
    # -- functioncall ----------------------------------------------------
    _k("AREAL_SYMPY_TIMEOUT_S", "float", 3.0,
       "Per-expression sympy equivalence-check timeout "
       "(functioncall/math_grader.py)."),
    _k("AREAL_PYEXEC_TIMEOUT", "float", 6.0,
       "Sandboxed python-answer execution timeout seconds "
       "(functioncall/python_answer.py)."),
    # -- pooled reward executor (system/reward_executor.py, docs/agentic.md)
    _k("AREAL_REXEC_WORKERS", "int", 2,
       "Warm sandbox worker subprocesses per reward-executor service. "
       "Workers are REUSED across jobs (no per-case fork); a job that "
       "times out or crashes costs one respawn, not the pool."),
    _k("AREAL_REXEC_QUEUE_MAX", "int", 64,
       "Bounded pending-job queue per executor service; submits beyond "
       "it shed 429 + Retry-After (deliberate backpressure, clients "
       "fail over / retry elsewhere)."),
    _k("AREAL_REXEC_MEM_MB", "int", 1024,
       "RLIMIT_AS ceiling (MiB) applied inside each warm sandbox "
       "worker at spawn (the code_verify guard, paid once per worker "
       "instead of once per case)."),
    _k("AREAL_REXEC_TIMEOUT_S", "float", 6.0,
       "Default per-job wall timeout on the executor pool; an overrun "
       "kills + respawns the one worker running the job."),
    _k("AREAL_REXEC_MAX_REUSE", "int", 0,
       "Jobs served per warm worker before a preventive recycle "
       "(leak hygiene for long campaigns); 0 = unlimited reuse."),
    # -- multi-tenant gateway (system/gateway.py, docs/serving.md) -------
    _k("AREAL_GW_TENANTS", "str", None,
       "Tenant table for the multi-tenant gateway: comma list of "
       "'name:api_key:weight:tokens_per_s:burst:max_streams' entries "
       "(e.g. 'acme:sk-acme:4:200:400:8'). Weight drives the "
       "fair-share quantum, tokens_per_s/burst the per-tenant token "
       "bucket, max_streams the concurrent-stream cap. The reserved "
       "'trainer' tenant (internal rollout traffic, infinite weight, "
       "never shed) always exists and may not be redeclared. Unset = "
       "no external tenants (every /v1 request answers 401)."),
    _k("AREAL_GW_FAIR_SHARE", "bool", True,
       "Weighted deficit-round-robin fair-share scheduling across "
       "tenant queues on the gateway. False = naive FIFO admission "
       "(the tenant_fairness bench's unfair A/B arm: documents the "
       "noisy-neighbor collapse)."),
    _k("AREAL_GW_CHUNK_TOKENS", "int", 32,
       "New-token budget per gateway->server /generate hop; between "
       "chunks the request re-schedules through the manager, so "
       "weight cutovers and reroutes interpose at chunk granularity "
       "(same contract as partial_rollout's trainer chunking)."),
    _k("AREAL_GW_MAX_INFLIGHT", "int", 8,
       "Upstream streams the gateway runs concurrently across ALL "
       "tenants; admitted requests beyond it wait in their tenant's "
       "fair-share queue (this cap is what makes the DRR order "
       "matter)."),
    _k("AREAL_GW_RETRY_AFTER_FLOOR_S", "float", 0.05,
       "Floor on the Retry-After seconds a gateway 429 carries; the "
       "advertised value is max(floor, the TENANT'S OWN bucket refill "
       "time for the request's cost) — never derived from fleet "
       "load."),
    _k("AREAL_GW_REQUEST_TIMEOUT_S", "float", 120.0,
       "Gateway->fleet HTTP session timeout and the default deadline "
       "budget minted for a /v1 request that arrives without "
       "X-Areal-Deadline."),
    _k("AREAL_GW_INTERNAL_TOKEN", "str", None,
       "Shared secret gating the gateway's INTERNAL surfaces: the "
       "/schedule_request trainer proxy and the /v1/usage + /metrics "
       "operator endpoints (presented as X-Areal-Gateway-Token or a "
       "Bearer token). Unset = each gateway instance mints a random "
       "token at startup. Either way the active token is published to "
       "name_resolve (names.gateway_internal_token) where rollout "
       "workers — but no external tenant — can read it; a caller "
       "without it gets 401, so tenant auth/quotas/metering can never "
       "be bypassed by POSTing the proxy directly."),
    _k("AREAL_GW_USAGE_COMPACT_EVERY", "int", 4096,
       "Usage-WAL compaction cadence: after this many journaled "
       "billing records the gateway folds the journal into one "
       "aggregated per-tenant row set (RolloutWAL.compact) and ages "
       "request ids out of the dedup set down to a bounded recent "
       "window — disk, replay time, and dedup memory stay O(cadence) "
       "instead of growing with lifetime traffic. 0 disables "
       "compaction (tests pinning raw-record replay)."),
    _k("AREAL_GW_MODELS", "str", None,
       "Model ids the fleet serves, comma list; the FIRST entry is "
       "the default a request without a meaningful OpenAI 'model' "
       "field maps to. Set -> the gateway resolves the request field "
       "against this list (unknown model 404, unentitled 403 via the "
       "tenant spec's optional 7th 'a|b' entitlement field), tags the "
       "scheduling meta with the resolved id so the manager routes "
       "to that model's pool only, and meters usage per (tenant, "
       "model). Unset = single-model legacy mode."),
    _k("AREAL_GW_TLS_CERT", "str", None,
       "PEM certificate chain for TLS termination on the gateway's "
       "tenant-facing listener; must be set together with "
       "AREAL_GW_TLS_KEY (exactly one set is a startup error, never "
       "a silent plaintext listener). The published discovery URL "
       "becomes https://. Production fleets normally terminate mTLS "
       "at the load balancer instead (docs/serving.md)."),
    _k("AREAL_GW_TLS_KEY", "str", None,
       "PEM private key paired with AREAL_GW_TLS_CERT (the in-process "
       "TLS terminator for single-box deployments and the selftest's "
       "self-signed arm)."),
    _k("AREAL_GW_TRAINER_VIA_GATEWAY", "bool", False,
       "Route rollout workers' partial-rollout SCHEDULING hops "
       "through the gateway's /schedule_request trainer-tenant proxy "
       "instead of straight at the manager (system/rollout_worker.py) "
       "— the fairness-accounting regression arm; allocate/finish "
       "stay on the manager either way."),
    # -- per-task staleness (system/buffer.py, docs/agentic.md) ----------
    _k("AREAL_TASK_STALENESS_WINDOWS", "str", "math:2,agentic:8",
       "Per-task buffer-admission version windows, 'task:window' comma "
       "list: a sample whose metadata carries a matching `task` tag is "
       "DROPPED at put_batch when current_train_step - version_end "
       "exceeds its window (math tight, agentic loose). Samples with "
       "no/unlisted task tag keep the global gserver-manager gate "
       "only."),
    # -- RPC substrate (base/rpc.py, docs/fault_tolerance.md) ------------
    _k("AREAL_RPC_ATTEMPTS", "int", 4,
       "Default attempts per cross-process RPC (base/rpc.py "
       "default_policy) — replaces the per-call-site magic numbers "
       "(e.g. generation_server's old hardcoded 4-attempt KV pull)."),
    _k("AREAL_RPC_BACKOFF_S", "float", 0.05,
       "Base of the jittered exponential backoff between RPC "
       "attempts; a server's Retry-After floors the computed wait."),
    _k("AREAL_RPC_BACKOFF_MAX_S", "float", 2.0,
       "Backoff ceiling for the default RPC policy."),
    _k("AREAL_RPC_TIMEOUT_S", "float", 30.0,
       "Per-attempt timeout CAP; the effective timeout is "
       "min(cap, remaining deadline budget) so a call with 2s left "
       "never waits 30s on one attempt."),
    _k("AREAL_RPC_HEDGE", "bool", True,
       "Enable hedged reads for idempotent hash-verified GETs "
       "(weight /weights/chunk, KV /kv/chunk) when multiple holders "
       "exist. The rpc_resilience bench A/B flips this."),
    _k("AREAL_RPC_HEDGE_DELAY_S", "float", 0.25,
       "Silence window after which a hedge request launches against "
       "the next holder; first success wins, losers are cancelled."),
    _k("AREAL_RPC_BREAKER_FAILS", "int", 5,
       "Consecutive failures that open a per-peer circuit breaker "
       "(closed -> open); sheds (429) never count."),
    _k("AREAL_RPC_BREAKER_COOLDOWN_S", "float", 2.0,
       "Open-breaker cooldown before ONE half-open probe is allowed "
       "through; probe success closes the circuit, failure re-opens."),
    _k("AREAL_RPC_REDISCOVERY_ATTEMPTS", "int", 64,
       "Manager-blip budget shared by partial_rollout and the rollout "
       "worker (base/rpc.py rediscovery_policy): control-plane "
       "restarts cost seconds and hit every client at once, so this "
       "is deliberately generous and separate from per-sample "
       "failure budgets."),
    _k("AREAL_RPC_REDISCOVERY_BACKOFF_MAX_S", "float", 5.0,
       "Backoff ceiling while rediscovering a restarted manager "
       "(jittered so thousands of workers don't hammer the successor "
       "the instant it registers)."),
    _k("AREAL_CHAOS_HTTP", "bool", False,
       "Arm the generation server's /configure chaos-control surface "
       "(runtime AREAL_FAULTS arming + hit introspection) so the "
       "all-points chaos campaign can sweep one long-lived subprocess "
       "fleet. OFF in production: with it off, /configure refuses "
       "fault specs with 403."),
    # -- system ----------------------------------------------------------
    _k("AREAL_WEIGHT_PLANE", "bool", False,
       "Arm the streaming weight-distribution plane without config "
       "plumbing (system/model_worker.py; GserverManagerConfig."
       "weight_plane is the first-class switch)."),
    _k("AREAL_WEIGHT_LOAD_RETRIES", "int", 40,
       "NFS weight-load retry attempts while a dump lands "
       "(system/weight_transfer.py)."),
    _k("AREAL_WEIGHT_LOAD_RETRY_S", "float", 0.25,
       "Sleep seconds between weight-load retries."),
    # -- bench -----------------------------------------------------------
    _k("AREAL_BENCH_BANK", "str", None,
       "Bench evidence-bank directory; unset = "
       "$TMPDIR/areal_bench_bank (bench/bank.py)."),
    _k("AREAL_BENCH_STATE_TTL_S", "float", 6 * 3600.0,
       "Age beyond which banked device state is stale for reporting "
       "(bench/bank.py, bench/report.py)."),
    _k("AREAL_BENCH_POLL_S", "float", 10.0,
       "Bench daemon device-poll interval seconds (bench/daemon.py)."),
    _k("AREAL_BENCH_WINDOW_HINT_S", "float", 90.0,
       "Optimistic device-window length hint the daemon plans phases "
       "against (bench/daemon.py)."),
    _k("AREAL_BENCH_MAX_ATTEMPTS", "int", 3,
       "Attempts per bench phase before the daemon banks a failure "
       "(bench/daemon.py)."),
    _k("AREAL_BENCH_DEVICE_BUDGET_S", "float", 300.0,
       "Per-phase device-seconds budget (bench/devices.py, "
       "bench/workloads.py)."),
    _k("AREAL_BENCH_INIT_BACKOFF_S", "float", 5.0,
       "Backoff after a failed device grab (bench/devices.py)."),
    _k("AREAL_BENCH_PHASE_DEADLINE_S", "float", None,
       "Hard wall-clock deadline override for one phase subprocess "
       "(bench/phases.py); unset = per-phase default."),
    _k("AREAL_BENCH_PHASE_MODULES", "str", "",
       "Comma list of extra modules to import for phase registration "
       "(bench/phases.py)."),
    _k("AREAL_XLA_CACHE_DIR", "str", None,
       "Persistent XLA compilation-cache dir; unset = "
       "$TMPDIR/areal_xla_cache (bench/runner.py)."),
    _k("AREAL_TTFT_SLO_MS", "float", None,
       "p99-TTFT SLO stamped onto open-loop bench records and gated "
       "by the report validator (bench/workloads.py); unset = no SLO."),
    _k("AREAL_OPENLOOP_SERVERS", "int", 2,
       "Open-loop bench: generation-server process count."),
    _k("AREAL_OPENLOOP_POINT_S", "float", 3.0,
       "Open-loop bench: seconds per arrival-rate sweep point."),
    _k("AREAL_OPENLOOP_RATES", "str", "0.25,1.0,3.0",
       "Open-loop bench: comma list of arrival-rate multipliers."),
    _k("AREAL_OPENLOOP_WATERMARK", "int", 8,
       "Open-loop bench: admission watermark (queued prompt kilotokens "
       "per server)."),
    _k("AREAL_OPENLOOP_MAX_RPS", "float", 12.0,
       "Open-loop bench: arrival-rate ceiling."),
    _k("AREAL_DISAGG_LONG_PLEN", "int", 768,
       "Disagg A/B bench: long-prefill prompt length."),
    _k("AREAL_DISAGG_SHORT_PLEN", "int", 16,
       "Disagg A/B bench: short (decode-stream) prompt length."),
    _k("AREAL_DISAGG_STREAMS", "int", 3,
       "Disagg A/B bench: concurrent decode streams."),
    _k("AREAL_DISAGG_STREAM_TOKENS", "int", 260,
       "Disagg A/B bench: max new tokens per decode stream."),
    _k("AREAL_DISAGG_N_LONG", "int", 5,
       "Disagg A/B bench: number of long prefills injected."),
    _k("AREAL_DISAGG_LONG_GAP_S", "float", 0.7,
       "Disagg A/B bench: gap between long-prefill injections."),
    _k("AREAL_DISAGG_LONG_MAX_NEW", "int", 8,
       "Disagg A/B bench: max new tokens per long prefill."),
]

REGISTRY: Dict[str, Knob] = {k.name: k for k in _KNOBS}
assert len(REGISTRY) == len(_KNOBS), "duplicate knob declaration"

# Accessor names areal_tpu/lint's env-knob checker recognizes as
# registry-routed reads (keep in sync with the functions below).
ACCESSOR_NAMES = (
    "get_raw", "get_str", "get_int", "get_float", "get_bool", "is_set",
)


class UndeclaredKnobError(KeyError):
    pass


def _knob(name: str) -> Knob:
    try:
        return REGISTRY[name]
    except KeyError:
        raise UndeclaredKnobError(
            f"{name} is not declared in areal_tpu.base.env_registry; "
            f"add a Knob entry (the env-knob lint checker enforces this)"
        ) from None


def get_raw(name: str) -> Optional[str]:
    """Raw string value, or None when unset/empty. For call sites with
    bespoke parsing; still validates the knob is declared."""
    _knob(name)
    v = os.environ.get(name)
    return v if v else None


def is_set(name: str) -> bool:
    _knob(name)
    return bool(os.environ.get(name))


def get_str(name: str) -> Optional[str]:
    k = _knob(name)
    v = os.environ.get(name)
    return v if v else k.default


def get_int(name: str) -> Optional[int]:
    k = _knob(name)
    v = os.environ.get(name)
    if not v:
        return k.default
    try:
        return int(v)
    except ValueError as e:
        raise ValueError(f"{name}={v!r}: expected an integer") from e


def get_float(name: str) -> Optional[float]:
    k = _knob(name)
    v = os.environ.get(name)
    if not v:
        return k.default
    try:
        return float(v)
    except ValueError as e:
        raise ValueError(f"{name}={v!r}: expected a float") from e


def get_bool(name: str) -> bool:
    k = _knob(name)
    v = os.environ.get(name)
    if not v:
        # unset OR empty falls back to the default, like every other
        # getter (the module contract) — not straight to False.
        return bool(k.default)
    return v.strip().lower() not in _FALSEY


def render_docs() -> str:
    """Markdown for docs/env_vars.md — generated, drift-gated; never
    hand-edit the output file."""
    lines = [
        "# `AREAL_*` environment knobs",
        "",
        "<!-- GENERATED FILE — do not edit. Source of truth: "
        "areal_tpu/base/env_registry.py. Regenerate with: "
        "python scripts/areal_lint.py --emit-env-docs docs/env_vars.md "
        "-->",
        "",
        "Every knob the system reads, generated from the registry the "
        "`env-knob` lint checker enforces. *Snapshot* knobs are read "
        "once at construction and pinned; editing them mid-run has no "
        "effect by design. Unset or empty values fall back to the "
        "default; `-` means the default is dynamic or None (see "
        "description).",
        "",
        "| Knob | Type | Default | Snapshot | Description |",
        "|---|---|---|---|---|",
    ]
    for k in sorted(REGISTRY.values(), key=lambda k: k.name):
        default = "-" if k.default is None else repr(k.default)
        snap = "yes" if k.snapshot else ""
        doc = k.doc.replace("|", "\\|")
        lines.append(
            f"| `{k.name}` | {k.kind} | {default} | {snap} | {doc} |"
        )
    lines.append("")
    return "\n".join(lines)

"""Multi-host runtime initialization over the name_resolve rendezvous.

Counterpart of the reference's NCCL global-comm setup
(realhf/impl/model/comm/global_comm.py:48-163, torch.distributed TCP
rendezvous): on TPU the collective fabric is managed by the JAX runtime,
so "setting up comm" reduces to electing a coordinator through
name_resolve and calling `jax.distributed.initialize` on every host of a
partition. ICI collectives then happen inside jitted programs; DCN traffic
(weight sync, trajectories) stays on the host side (ZMQ / shared FS).
"""

from __future__ import annotations

import dataclasses
import os
from typing import List, Optional

from areal_tpu.base import logging as areal_logging
from areal_tpu.base import name_resolve, names, network

logger = areal_logging.getLogger("distributed")


@dataclasses.dataclass
class HostGroupInfo:
    """What a host process knows after joining its partition's group."""

    coordinator_address: str
    process_id: int
    num_processes: int


def setup_host_group(
    experiment_name: str,
    trial_name: str,
    group_name: str,
    host_rank: int,
    n_hosts: int,
    timeout: float = 300.0,
) -> HostGroupInfo:
    """Elect a coordinator via name_resolve and initialize jax.distributed.

    Single-host (n_hosts == 1) is a no-op besides returning the info —
    jax.distributed is not required, and local meshes work as-is.
    """
    if n_hosts == 1:
        return HostGroupInfo("localhost", 0, 1)

    key = names.distributed_coordinator(experiment_name, trial_name) + f"/{group_name}"
    if host_rank == 0:
        addr = f"{network.gethostip()}:{network.find_free_port()}"
        name_resolve.add(key, addr, keepalive_ttl=timeout, replace=True)
    else:
        addr = name_resolve.wait(key, timeout=timeout)

    import jax

    jax.distributed.initialize(
        coordinator_address=addr,
        num_processes=n_hosts,
        process_id=host_rank,
    )
    logger.info(
        "joined host group %s as %d/%d (coordinator %s)",
        group_name, host_rank, n_hosts, addr,
    )
    return HostGroupInfo(addr, host_rank, n_hosts)

"""Tenant-gateway e2e (ISSUE 19 acceptance).

`test_async_ppo_through_gateway`: the full async-PPO stack with
AREAL_GW_TRAINER_VIA_GATEWAY armed — every trainer scheduling hop rides
the gateway's /schedule_request proxy as the reserved never-shed
``trainer`` tenant, and the run still trains 2 steps with zero sheds
(the regression pin for internal traffic being rate-limited behind
external tenants).

`test_gateway_acceptance_multi_tenant`: 2 real GenerationServer
processes + real manager + a gateway SUBPROCESS and 3 tenant roles —
an aggressor flooding at 3x its stream cap (shed with Retry-After from
its OWN bucket), an interactive victim whose p99 TTFT must hold near
its solo baseline while the flood runs, and trainer-proxy traffic with
zero failures — then the gateway is SIGKILLed mid-life and restarted
on the same usage WAL: the replayed ledger must match the pre-kill
rows AND the client-side token tally exactly (exactly-once billing
across restarts)."""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
import uuid

import numpy as np
import pytest

from areal_tpu.api.config import (
    AgentAbstraction,
    DatasetAbstraction,
    EnvServiceAbstraction,
    ModelAbstraction,
)
from areal_tpu.api.system_api import (
    ExperimentConfig,
    GenerationServerConfig,
    GserverManagerConfig,
    RolloutWorkerConfig,
)
from areal_tpu.base import name_resolve, names
from areal_tpu.system.controller import LocalController
from areal_tpu.system.gateway import GatewayService
from tests import fixtures
from tests.system.test_async_e2e import _deflaked_env, _trainer_parts
from tests.system.test_e2e_experiments import _mk_tokenizer_files

pytestmark = pytest.mark.serial

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _gw_req(url, path, payload=None, key=None, timeout=120.0,
            headers=None):
    """(status, headers, parsed-json) against a gateway; 4xx/5xx are
    returned, not raised."""
    h = {"Content-Type": "application/json"}
    if key:
        h["Authorization"] = f"Bearer {key}"
    h.update(headers or {})
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(url + path, data, h)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, dict(r.headers), json.loads(r.read())
    except urllib.error.HTTPError as e:
        body = e.read()
        try:
            parsed = json.loads(body)
        except Exception:
            parsed = {"raw": body.decode(errors="replace")}
        return e.code, dict(e.headers), parsed


@pytest.mark.slow
def test_async_ppo_through_gateway(tmp_path, monkeypatch):
    """Satellite regression: a live PPO rollout stream scheduled
    THROUGH the gateway — tagged as the reserved trainer tenant, never
    queued, never shed."""
    exp, trial = f"e2e-gwppo-{uuid.uuid4().hex[:6]}", "t0"
    rows, tok_dir = _mk_tokenizer_files(tmp_path)
    mc_rows = [
        r for r in fixtures.make_math_code_rows(12, seed=21)
        if r["task"] == "math"
    ]
    data_path = fixtures.write_jsonl(mc_rows, tmp_path / "mc.jsonl")
    nr_root = str(tmp_path / "name_resolve")

    worker_env = _deflaked_env(tmp_path, monkeypatch)
    worker_env["AREAL_GW_TRAINER_VIA_GATEWAY"] = "1"

    # The gateway rides the run's name_resolve plane; it can only start
    # once the manager registered, so a sidecar thread waits for it the
    # same way rollout workers do.
    name_resolve.reconfigure("nfs", record_root=nr_root)
    holder = {}

    def _start_gateway():
        addr = name_resolve.wait(
            names.gen_server_manager(exp, trial), timeout=300
        )
        svc = GatewayService(
            exp, trial, manager_addr=addr,
            tenant_spec="acme:sk-acme:1:100000:200000:8",
            usage_wal_path=str(tmp_path / "gw_usage.jsonl"),
        )
        holder["svc"] = svc
        svc.start()

    gw_thread = threading.Thread(target=_start_gateway, daemon=True)
    gw_thread.start()

    model_args, mw, master = _trainer_parts(exp, trial, tok_dir)
    gen_server = GenerationServerConfig(
        experiment_name=exp,
        trial_name=trial,
        server_index=0,
        model=ModelAbstraction("tpu_transformer", args=model_args),
        tokenizer_path=tok_dir,
        max_concurrent_requests=4,
        max_seq_len=256,
        decode_block_steps=4,
    )
    gserver_mgr = GserverManagerConfig(
        experiment_name=exp,
        trial_name=trial,
        model_name="actor",
        n_servers=1,
        train_batch_size=2,
        max_head_offpolicyness=100,
    )
    rollout = RolloutWorkerConfig(
        experiment_name=exp,
        trial_name=trial,
        worker_index=0,
        n_rollout_workers=1,
        n_pullers=1,
        agent=AgentAbstraction(
            "math-single-step",
            args=dict(gconfig=dict(n=2, max_new_tokens=8)),
        ),
        env=EnvServiceAbstraction("math-code-single-step"),
        datasets=[
            DatasetAbstraction(
                "math_code_prompt", args=dict(dataset_path=data_path)
            )
        ],
        tokenizer_path=tok_dir,
        max_concurrent_rollouts=4,
    )
    cfg = ExperimentConfig(
        experiment_name=exp,
        trial_name=trial,
        master=master,
        model_workers=[mw],
        rollout_workers=[rollout],
        gserver_manager=gserver_mgr,
        generation_servers=[gen_server],
    )
    ctl = LocalController(
        cfg,
        name_resolve_cfg={"backend": "nfs", "record_root": nr_root},
        worker_env=worker_env,
    )
    try:
        result = ctl.run()
        assert result["global_step"] == 2

        svc = holder["svc"]
        # Every trainer scheduling hop rode the proxy...
        assert svc._trainer_sched > 0
        # ...and internal traffic was NEVER queued or shed behind
        # external tenants.
        assert svc.counters["shed_total"] == 0
        st, _, usage = _gw_req(
            svc.address, "/v1/usage",
            headers={"X-Areal-Gateway-Token": svc.internal_token})
        assert st == 200
        trow = usage["tenants"]["trainer"]
        assert trow["sched_requests"] == svc._trainer_sched
        assert trow["sheds"] == 0
    finally:
        svc = holder.get("svc")
        if svc is not None:
            svc.stop()
        from areal_tpu.base import tracing

        tracing.reconfigure()


# ----------------------------------------------------------------------
# Multi-tenant acceptance over a real-process fleet
# ----------------------------------------------------------------------

PLEN = 32
MAX_NEW = 6


def _spawn_gateway(fleet, tenants, wal, log_path, extra_env=None,
                   models=None):
    env = dict(fleet._env)
    env.update(extra_env or {})
    log_f = open(log_path, "a")
    argv = [
        sys.executable, "-m", "areal_tpu.system.gateway",
        "--experiment", fleet.exp, "--trial", fleet.trial,
        "--manager-addr", fleet.manager_addr(),
        "--tenants", tenants,
        "--usage-wal", wal,
        "--name-resolve-root", fleet._nr,
    ]
    if models:
        argv += ["--models", models]
    p = subprocess.Popen(
        argv, env=env, cwd=REPO, stdout=log_f, stderr=subprocess.STDOUT,
    )
    p._log_f = log_f  # closed by the caller's finally
    return p


def _wait_gateway(fleet, proc, not_url=None, timeout_s=60.0):
    """Poll name_resolve until the gateway registered a LIVE url
    (different from `not_url` across restarts); returns (url,
    internal_token) — the token gates the operator surfaces and the
    trainer proxy."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"gateway died at startup (rc={proc.returncode})"
            )
        try:
            url = name_resolve.get(
                names.gateway_url(fleet.exp, fleet.trial, 0)
            )
            token = name_resolve.get(
                names.gateway_internal_token(fleet.exp, fleet.trial, 0)
            )
            if url and token and url != not_url:
                st, _, _ = _gw_req(url, "/health", timeout=5.0)
                if st == 200:
                    return url, token
        except Exception:
            pass
        time.sleep(0.2)
    raise TimeoutError("gateway never registered a live url")


class _Tally:
    """Client-side ground truth: what each tenant actually received."""

    def __init__(self):
        self.lock = threading.Lock()
        self.rows = {}

    def add(self, tenant, status, body):
        with self.lock:
            r = self.rows.setdefault(tenant, {
                "requests": 0, "sheds": 0,
                "prompt_tokens": 0, "completion_tokens": 0,
            })
            if status == 200:
                r["requests"] += 1
                u = body["usage"]
                r["prompt_tokens"] += u["prompt_tokens"]
                r["completion_tokens"] += u["completion_tokens"]
            elif status == 429:
                r["sheds"] += 1
            else:
                raise AssertionError(f"{tenant}: unexpected {status}: {body}")


@pytest.mark.slow
def test_gateway_acceptance_multi_tenant(tmp_path):
    from areal_tpu.bench.fleet import ProcessFleet
    from areal_tpu.bench.workloads import _FLEET_SRV, _OPENLOOP_MODEL

    rng = np.random.RandomState(7)
    tenants = (
        "agg:sk-agg:1:100000:200000:4,"
        "solo:sk-solo:4:100000:200000:8,"
        "victim:sk-vic:4:100000:200000:8"
    )
    wal = str(tmp_path / "gw_usage.jsonl")
    gw_log = str(tmp_path / "gateway.log")
    tally = _Tally()
    gw_procs = []
    fleet_dir = tmp_path / "fleet"
    fleet_dir.mkdir()
    with ProcessFleet(
        _OPENLOOP_MODEL, [dict(_FLEET_SRV)] * 2, tag="gwacc",
        tmp_dir=str(fleet_dir),
    ) as fleet:
        try:
            gw_procs.append(_spawn_gateway(
                fleet, tenants, wal, gw_log,
                # A tight dispatch window so contention shows up AT THE
                # GATEWAY (where fair-share arbitrates), not just on
                # the servers.
                extra_env={"AREAL_GW_MAX_INFLIGHT": "4"},
            ))
            url, gw_tok = _wait_gateway(fleet, gw_procs[0])
            op_hdr = {"X-Areal-Gateway-Token": gw_tok}

            def completion(tenant_key, tenant, i):
                st, hdrs, body = _gw_req(url, "/v1/completions", {
                    "prompt": rng.randint(1, 200, size=PLEN).tolist(),
                    "max_tokens": MAX_NEW, "stream": False,
                }, key=tenant_key, timeout=180.0)
                tally.add(tenant, st, body)
                return st, hdrs, body

            # ---- Solo baseline: the interactive class alone on an
            # idle fleet.
            for i in range(6):
                st, _, body = completion("sk-solo", "solo", i)
                assert st == 200, body
                assert len(body["choices"][0]["token_ids"]) == MAX_NEW
            _, _, usage = _gw_req(url, "/v1/usage", headers=op_hdr)
            solo_p99 = usage["tenants"]["solo"]["ttft_p99_ms"]
            assert solo_p99 > 0.0

            # ---- Contention: the aggressor floods at 3x its stream
            # cap while the victim keeps its interactive cadence.
            agg_done = []

            def agg_fire(i):
                st, hdrs, body = completion("sk-agg", "agg", i)
                if st == 429:
                    # The Retry-After is the AGGRESSOR's own bucket's
                    # advice, never the fleet's.
                    ra = float(hdrs["Retry-After"])
                    assert ra >= 0.05
                    assert body["error"]["retry_after"] == pytest.approx(
                        ra, abs=1e-3)
                agg_done.append(st)

            threads = [
                threading.Thread(target=agg_fire, args=(i,), daemon=True)
                for i in range(12)
            ]
            for th in threads:
                th.start()
            for i in range(6):
                st, _, body = completion("sk-vic", "victim", i)
                assert st == 200, body
            for th in threads:
                th.join(timeout=300)
            assert len(agg_done) == 12

            _, _, usage = _gw_req(url, "/v1/usage", headers=op_hdr)
            rows = usage["tenants"]
            # The aggressor was shed (3x its cap of 4 concurrent
            # streams) and NOBODY else was.
            assert rows["agg"]["sheds"] >= 1, rows
            assert rows["victim"]["sheds"] == 0
            assert rows["solo"]["sheds"] == 0
            # Fairness held: the victim's p99 TTFT (admission clock,
            # queue wait included) stayed within 2x its solo baseline
            # plus bounded CPU-box scheduling noise.
            vic_p99 = rows["victim"]["ttft_p99_ms"]
            assert vic_p99 <= 2.0 * solo_p99 + 1500.0, (
                f"victim p99 {vic_p99}ms vs solo {solo_p99}ms"
            )

            # ---- Trainer stream through the proxy (internal-token
            # authenticated): zero failures.
            for i in range(6):
                st, _, sched = _gw_req(url, "/schedule_request", {
                    "qid": f"train{i}", "prompt_len": PLEN,
                    "new_token_budget": MAX_NEW,
                }, timeout=60.0, headers=op_hdr)
                assert st == 200 and "url" in sched, sched
                st2, _, out = _gw_req(sched["url"], "/generate", {
                    "qid": f"train{i}",
                    "input_ids": rng.randint(1, 200, size=PLEN).tolist(),
                    "gconfig": {"max_new_tokens": MAX_NEW,
                                "greedy": True},
                }, timeout=180.0)
                assert st2 == 200 and len(out["output_ids"]) == MAX_NEW
            _, _, usage = _gw_req(url, "/v1/usage", headers=op_hdr)
            assert usage["tenants"]["trainer"]["sched_requests"] == 6
            assert usage["tenants"]["trainer"]["sheds"] == 0

            # ---- Exactly-once billing across a SIGKILL + restart.
            pre = {
                n: {k: r[k] for k in ("requests", "sheds",
                                      "prompt_tokens",
                                      "completion_tokens")}
                for n, r in usage["tenants"].items() if n != "trainer"
            }
            # The ledger already matches the client-side ground truth
            # token for token...
            assert pre == tally.rows
            gw_procs[0].kill()
            gw_procs[0].wait(timeout=15)
            gw_procs.append(_spawn_gateway(
                fleet, tenants, wal, gw_log,
                extra_env={"AREAL_GW_MAX_INFLIGHT": "4"},
            ))
            url2, gw_tok2 = _wait_gateway(fleet, gw_procs[1],
                                          not_url=url)
            _, _, usage2 = _gw_req(
                url2, "/v1/usage",
                headers={"X-Areal-Gateway-Token": gw_tok2})
            # ...and the WAL replay reconstructs EXACTLY those rows:
            # nothing lost, nothing double-billed.
            assert usage2["usage_replayed"] > 0
            post = {
                n: {k: r[k] for k in ("requests", "sheds",
                                      "prompt_tokens",
                                      "completion_tokens")}
                for n, r in usage2["tenants"].items() if n != "trainer"
            }
            assert post == pre
            # The restarted gateway still serves.
            st, _, body = _gw_req(url2, "/v1/completions", {
                "prompt": rng.randint(1, 200, size=PLEN).tolist(),
                "max_tokens": MAX_NEW, "stream": False,
            }, key="sk-solo", timeout=180.0)
            assert st == 200, body
        finally:
            for p in gw_procs:
                if p.poll() is None:
                    p.kill()
                try:
                    p._log_f.close()
                except Exception:
                    pass

"""Benchmark presets for the offline eval harness.

Role counterpart of the reference's evaluation/{data_loader,examples,
utils,parser}.py (prompt templates keyed by model family at
evaluation/utils.py:79-175, per-dataset few-shot demos at examples.py,
per-dataset question/ground-truth field parsing at parser.py:578-720):
given a benchmark NAME and a jsonl file, this module knows which fields
hold the question and the ground-truth answer, which prompt format the
model family expects, and how many worked examples to prepend — so
`math_eval.py benchmark=math500 ...` reproduces the reference's
quality-table methodology without per-run plumbing.

Design differences from the reference (deliberate): templates are small
dataclasses with a `wrap()` method instead of 3-tuples + format-string
special cases; ground truth resolves through ordered field candidates
plus an optional per-benchmark extractor instead of a 150-line if/elif
ladder; few-shot demos are stored once in the template-agnostic
(question, reasoning, answer) form and each template renders them its
own way.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Callable, List, Optional, Sequence, Tuple

# ---------------------------------------------------------------------------
# Prompt templates
# ---------------------------------------------------------------------------

# Doubled braces: template strings pass through str.format exactly once.
BOXED_INSTRUCTION = (
    "Please reason step by step, and put your final answer within "
    "\\boxed{{}}."
)


@dataclasses.dataclass(frozen=True)
class PromptTemplate:
    """Renders (few-shot demos +) a question into a model-ready prompt.

    prefix (optional) emits ONCE at the very start — chat formats put
    the system turn there so demos cannot appear before it; demo_format
    receives (question, full worked answer) pairs joined by demo_sep;
    the final question renders via question_format after the demos."""

    name: str
    question_format: str
    demo_format: str = "{question}\n{answer}"
    demo_sep: str = "\n\n"
    prefix: str = ""

    def wrap(self, question: str,
             shots: Sequence[Tuple[str, str]] = ()) -> str:
        parts = [self.demo_format.format(question=q, answer=a)
                 for q, a in shots]
        parts.append(self.question_format.format(question=question))
        # prefix goes through .format() too: every template string gets
        # exactly one format pass (escaped {{}} in BOXED_INSTRUCTION).
        return self.prefix.format() + self.demo_sep.join(parts)


PROMPT_TEMPLATES = {
    # Bare continuation, no chat markup: base models / quick smoke evals.
    "direct": PromptTemplate(
        name="direct",
        question_format="Question: {question}\nAnswer:",
        demo_format="Question: {question}\nAnswer: {answer}",
    ),
    # Few-shot chain-of-thought in plain text (the classic CoT setup).
    "cot": PromptTemplate(
        name="cot",
        question_format="Question: {question}\nAnswer:",
        demo_format="Question: {question}\nAnswer: {answer}",
        demo_sep="\n\n\n",
    ),
    # Plain instruction + boxed answer, no chat markup.
    "boxed": PromptTemplate(
        name="boxed",
        question_format="{question}\n" + BOXED_INSTRUCTION + "\n",
    ),
    # Qwen2.5-style ChatML with the boxed instruction in the system turn
    # (the format the reference's RL-trained Qwen checkpoints expect).
    "chatml-boxed": PromptTemplate(
        name="chatml-boxed",
        prefix=(
            "<|im_start|>system\n" + BOXED_INSTRUCTION + "<|im_end|>\n"
        ),
        question_format=(
            "<|im_start|>user\n{question}<|im_end|>\n"
            "<|im_start|>assistant\n"
        ),
        demo_format=(
            "<|im_start|>user\n{question}<|im_end|>\n"
            "<|im_start|>assistant\n{answer}<|im_end|>\n"
        ),
        demo_sep="",
    ),
    # Multiple-choice (GPQA/MMLU-style rows whose question text already
    # carries the lettered options): the boxed answer is the LETTER.
    "boxed-choice": PromptTemplate(
        name="boxed-choice",
        question_format=(
            "{question}\nPlease reason step by step, and put the letter "
            "of the correct option within \\boxed{{}}.\n"
        ),
    ),
    # PAL: the model writes a python program whose solution() returns
    # the answer; math_eval answer_mode='python' executes it in the
    # sandboxed subprocess (functioncall/python_answer.py — the role of
    # the reference's evaluation/python_executor.py).
    "pal": PromptTemplate(
        name="pal",
        question_format=(
            "Question: {question}\n"
            "Write a Python program that computes the answer; define "
            "solution() returning it.\n\n```python\n"
        ),
        demo_format="Question: {question}\n{answer}",
        demo_sep="\n---\n",
    ),
    # DeepSeek-R1-Distill family markup with an opened think block (the
    # flagship bench model family; see docs/perf_notes.md).
    "r1-distill": PromptTemplate(
        name="r1-distill",
        question_format=(
            "<｜User｜>{question}\n" + BOXED_INSTRUCTION
            + "<｜Assistant｜><think>\n"
        ),
    ),
}

# ---------------------------------------------------------------------------
# Few-shot worked examples
# ---------------------------------------------------------------------------
# Template-agnostic (question, worked answer) demos, written for this
# repo. GSM8K-grade arithmetic with explicit reasoning and a final
# "The answer is N." that extract_answer picks up; the boxed variant
# swaps the terminal form.

MATH_FEW_SHOT: List[Tuple[str, str]] = [
    (
        "A bookshelf holds 4 rows of 9 books. If 7 books are checked "
        "out, how many books remain on the shelf?",
        "The shelf starts with 4 rows of 9 books, which is 4 * 9 = 36 "
        "books. After 7 are checked out, 36 - 7 = 29 remain. "
        "The answer is 29.",
    ),
    (
        "Tickets cost $12 for adults and $5 for children. What do 2 "
        "adults and 3 children pay in total?",
        "Two adult tickets cost 2 * 12 = 24 dollars. Three child "
        "tickets cost 3 * 5 = 15 dollars. Together that is 24 + 15 = "
        "39 dollars. The answer is 39.",
    ),
    (
        "A cyclist rides 15 km per hour. How far does she ride in 2.5 "
        "hours?",
        "Distance is speed times time: 15 * 2.5 = 37.5 km. "
        "The answer is 37.5.",
    ),
    (
        "A farmer plants 126 seeds in rows of 14. How many rows does "
        "he plant?",
        "Dividing the seeds into rows of 14 gives 126 / 14 = 9 rows. "
        "The answer is 9.",
    ),
]


# PAL-style demos: programs whose solution() returns the answer.
PAL_FEW_SHOT: List[Tuple[str, str]] = [
    (
        "A bookshelf holds 4 rows of 9 books. If 7 books are checked "
        "out, how many books remain on the shelf?",
        "```python\n"
        "def solution():\n"
        "    total = 4 * 9\n"
        "    return total - 7\n"
        "```",
    ),
    (
        "Tickets cost $12 for adults and $5 for children. What do 2 "
        "adults and 3 children pay in total?",
        "```python\n"
        "def solution():\n"
        "    return 2 * 12 + 3 * 5\n"
        "```",
    ),
]


def boxed_shots(shots: Sequence[Tuple[str, str]]) -> List[Tuple[str, str]]:
    """Rewrite 'The answer is N.' demo endings into \\boxed{N} form so
    few-shot demos match the boxed instruction the template gives."""
    out = []
    for q, a in shots:
        if "The answer is " in a:
            head, tail = a.rsplit("The answer is ", 1)
            ans = tail.rstrip().rstrip(".")
            a = head + "The final answer is $\\boxed{" + ans + "}$."
        out.append((q, a))
    return out


# ---------------------------------------------------------------------------
# Benchmark presets
# ---------------------------------------------------------------------------


def _gsm8k_gt(row: dict) -> Optional[str]:
    """GSM8K stores 'reasoning #### answer' in the answer field."""
    ans = row.get("answer")
    if isinstance(ans, str) and "####" in ans:
        return ans.rsplit("####", 1)[1].strip().replace(",", "")
    return ans


@dataclasses.dataclass(frozen=True)
class BenchmarkPreset:
    """Field mapping + eval defaults for a named benchmark jsonl.

    question_keys/answer_keys are ordered candidates (public dumps of
    the same benchmark disagree on field names); answer_fn, when set,
    overrides the key lookup entirely."""

    name: str
    question_keys: Tuple[str, ...] = ("problem", "question", "prompt")
    answer_keys: Tuple[str, ...] = ("answer", "solution", "solutions")
    answer_fn: Optional[Callable[[dict], Optional[str]]] = None
    prompt_type: str = "boxed"
    num_shots: int = 0
    max_new_tokens: int = 4096
    # Multi-sample defaults: small contest sets (AIME: 30 problems) are
    # conventionally reported as avg@k/pass@k over many samples.
    n_samples: int = 1
    temperature: float = 0.6

    def question(self, row: dict) -> str:
        for k in self.question_keys:
            if row.get(k):
                return str(row[k])
        raise KeyError(
            f"benchmark {self.name}: no question field among "
            f"{self.question_keys} in row keys {sorted(row)}"
        )

    def ground_truth(self, row: dict):
        if self.answer_fn is not None:
            val = self.answer_fn(row)
        else:
            val = next(
                (row[k] for k in self.answer_keys
                 if row.get(k) is not None),
                None,
            )
        if val is None:
            # Raise like question() does — on BOTH paths: a silent None
            # would grade every sample wrong against the string 'None'
            # and report a plausible-looking 0.0 accuracy.
            raise KeyError(
                f"benchmark {self.name}: no ground-truth answer found "
                f"in row keys {sorted(row)}"
            )
        return val


BENCHMARKS = {
    "aime24": BenchmarkPreset(
        name="aime24", n_samples=8, max_new_tokens=8192,
    ),
    "aime25": BenchmarkPreset(
        name="aime25", n_samples=8, max_new_tokens=8192,
    ),
    "amc23": BenchmarkPreset(
        name="amc23", n_samples=4, max_new_tokens=4096,
    ),
    "math500": BenchmarkPreset(
        name="math500", max_new_tokens=4096,
    ),
    # GPQA-diamond-style multiple choice: the question field already
    # carries the lettered options; ground truth is the letter.
    "gpqa_diamond": BenchmarkPreset(
        name="gpqa_diamond",
        question_keys=("question", "problem", "prompt"),
        answer_keys=("answer",),
        prompt_type="boxed-choice",
        max_new_tokens=2048,
    ),
    "gsm8k": BenchmarkPreset(
        name="gsm8k",
        answer_fn=_gsm8k_gt,
        prompt_type="cot",
        num_shots=4,
        max_new_tokens=512,
    ),
    # Generic preset for the repo's own prompt/solutions jsonl schema
    # (datasets/math_code_prompt.py), zero-shot boxed. Named "generic",
    # NOT "default": math_eval's no-preset path labels results
    # differently ("none"/"verbatim"), and one label must never cover
    # two prompt behaviors.
    "generic": BenchmarkPreset(name="generic"),
}


def load_benchmark(data_path: str, preset: BenchmarkPreset) -> List[dict]:
    """jsonl -> [{query_id, question, gt}], via the preset's field map."""
    rows = []
    with open(data_path) as f:
        for i, line in enumerate(f):
            if not line.strip():
                continue
            raw = json.loads(line)
            rows.append(
                dict(
                    query_id=str(raw.get("query_id", raw.get("idx", i))),
                    question=preset.question(raw),
                    gt=preset.ground_truth(raw),
                )
            )
    return rows


def build_prompt(question: str, prompt_type: str, num_shots: int) -> str:
    template = PROMPT_TEMPLATES[prompt_type]
    if prompt_type == "pal":
        pool = PAL_FEW_SHOT
    elif prompt_type == "boxed-choice":
        # No letter-answer demos exist; numeric math demos would
        # contradict the boxed-LETTER instruction and bias the model —
        # num_shots > 0 fails loudly via the length check below.
        pool = []
    else:
        pool = MATH_FEW_SHOT
    if num_shots > len(pool):
        # Refuse rather than silently truncate: the result metadata
        # records the REQUESTED shot count, and a published "8-shot"
        # number that actually ran 4-shot would misstate methodology.
        raise ValueError(
            f"num_shots={num_shots} but only {len(pool)} few-shot "
            f"demos are available for {prompt_type!r} "
            f"(evaluation/presets.py)"
        )
    shots = pool[:num_shots]
    if "boxed" in prompt_type or prompt_type == "r1-distill":
        shots = boxed_shots(shots)
    return template.wrap(question, shots)

"""Verifier-based reward interface (math + code).

Counterpart of realhf/impl/model/interface/math_rw_interface.py
(MultiTaskRewardInterface:518): decodes generated sequences, dispatches
each to the math grader or code verifier by task tag, and emits per-
sequence rewards (+5 / -5 by default, matching the reference's convention).
Runs on the host — no model forward needed.
"""

from __future__ import annotations

import dataclasses
import json
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List

import numpy as np

from areal_tpu.api.data_api import MicroBatchSpec, SequenceSample
from areal_tpu.api.model_api import Model, ModelInterface, register_interface
from areal_tpu.base import logging as areal_logging
from areal_tpu.functioncall.code_verify import code_verify
from areal_tpu.functioncall.math_grader import grade_answer

logger = areal_logging.getLogger("reward")


def verify_one(task: str, text: str, answer_info: Any) -> bool:
    """Grade one generated answer against its reference (math grader /
    code testcases). Shared by the reward MFC and the PPO interface's
    best-of-k selection."""
    if task == "code":
        cases = answer_info
        if isinstance(cases, str):
            cases = json.loads(cases)
        return code_verify(text, cases)
    return grade_answer(text, answer_info)


def verify_all(jobs: List[tuple], max_workers: int = 8) -> List[bool]:
    """Verify (task, text, answer) jobs — against the remote verifier
    service when FUNCTIONCALL_SERVICE_DOMAIN is set (batched, with
    retries; reference math_rw_interface.py:37-39), a local thread pool
    otherwise. Shared by the reward MFC and best-of-k selection."""
    from areal_tpu.functioncall import remote

    if remote.remote_enabled():
        oks: List[bool] = [False] * len(jobs)
        by_task: Dict[str, List[int]] = {}
        for i, (task, _, _) in enumerate(jobs):
            by_task.setdefault(task, []).append(i)
        for task, idxs in by_task.items():
            payloads = []
            for i in idxs:
                _, text, answer = jobs[i]
                key = "test_cases" if task == "code" else "answer"
                payloads.append({"uid": str(i), "solution": text, key: answer})
            results = remote.batch_verify(payloads, task)
            for i, ok in zip(idxs, results):
                oks[i] = ok
        return oks

    with ThreadPoolExecutor(max_workers=max_workers) as ex:
        return list(ex.map(lambda args: verify_one(*args), jobs))


@dataclasses.dataclass
class MultiTaskRewardInterface(ModelInterface):
    correct_reward: float = 5.0
    wrong_reward: float = -5.0
    max_workers: int = 8
    check_verifier_status: bool = False

    def _verify_one(self, task: str, text: str, answer_info: Any) -> bool:
        return verify_one(task, text, answer_info)

    def _verify_all(self, jobs: List[tuple]) -> List[bool]:
        return verify_all(jobs, max_workers=self.max_workers)

    def inference(
        self, model: Model, input_: SequenceSample, mb_spec: MicroBatchSpec
    ) -> SequenceSample:
        tokenizer = model.tokenizer
        flat = np.asarray(input_.data["packed_input_ids"])
        pm = np.asarray(input_.data.get("prompt_mask")) if "prompt_mask" in input_.keys else None

        texts: List[str] = []
        offset = 0
        seq_prompt_ids: List[int] = []  # prompt index per sequence
        for pi, sl in enumerate(input_.seqlens["packed_input_ids"]):
            for l in sl:
                ids = flat[offset : offset + l]
                if pm is not None:
                    ids = ids[pm[offset : offset + l] == 0]  # response only
                texts.append(tokenizer.decode(ids.tolist()))
                seq_prompt_ids.append(pi)
                offset += l

        answers = input_.metadata.get("solutions") or input_.metadata.get("answers")
        tasks = input_.metadata.get("tasks") or ["math"] * input_.bs
        if answers is None:
            raise ValueError("reward interface needs 'solutions'/'answers' metadata")

        jobs = [
            (tasks[pi], texts[si], answers[pi])
            for si, pi in enumerate(seq_prompt_ids)
        ]
        oks = self._verify_all(jobs)
        rewards = np.where(
            np.asarray(oks), self.correct_reward, self.wrong_reward
        ).astype(np.float32)

        n_per_prompt = [len(sl) for sl in input_.seqlens["packed_input_ids"]]
        out = SequenceSample(
            ids=list(input_.ids),
            keys={"rewards"},
            data={"rewards": rewards},
            seqlens={"rewards": [[1] * n for n in n_per_prompt]},
            metadata={
                "scores": [
                    float(
                        np.mean(
                            [ok for si, ok in zip(seq_prompt_ids, oks) if si == pi]
                        )
                    )
                    for pi in range(input_.bs)
                ]
            },
        )
        return out


register_interface("rw-math-code", MultiTaskRewardInterface)

"""Streaming weight-distribution plane: network-native weight updates.

The disk-mediated path (system/weight_transfer.py) makes every
generation server re-read the full checkpoint from NFS on every version
bump — O(N * model_size) trainer/NFS egress per update. This module is
the network-native replacement:

- :class:`WeightPlaneSource` — the trainer-side dump rank (or the
  gserver manager's NFS-backed fallback) exposes the existing raw-bin
  dump format (``params-v{N}.bin`` + manifest) over chunked HTTP with
  per-chunk content hashes and Range resume (base/chunking.py).
- :func:`plan_fanout` — the gserver manager computes a degree-bounded
  peer-fanout tree per version: the origin uploads each byte ONCE (to
  its direct children); servers that already hold version N serve
  chunks to their siblings, so fleet-wide distribution costs the origin
  O(1) full payloads plus peer hops.
- :class:`PeerStoreServer` — a standalone holder serving a fetched
  :class:`~areal_tpu.engine.weight_client.ChunkStore` over the same
  ``/weights/...`` contract; generation servers mount the equivalent
  handlers on their existing HTTP app, and the bench workload
  (``weight_update`` phase) uses this class directly.

Transfer is overlapped with serving: a server prefetches version-N
bytes into host memory while still serving N-1; the cutover (interrupt
+ device swap, ``ServingEngine.cutover_params``) is a separate, short,
separately-measured window. Failure handling composes with the PR 1
health plane: a peer that dies mid-transfer is evicted and its
children re-fanout from surviving holders or the origin.
"""

from __future__ import annotations

import asyncio
import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from aiohttp import web

from areal_tpu.base import logging
from areal_tpu.base.chunking import (
    CHUNK_SCHEMA,
    DEFAULT_CHUNK_BYTES,
    StreamChunker,
    build_chunk_index,
    chunk_spans,
    gather_stream,
    shard_stream_plan,
    stream_prefix,
)
from areal_tpu.base.fault_injection import faults

logger = logging.getLogger("weight_plane")

_MANIFEST = "params.json"  # weight_transfer's manifest name


# ----------------------------------------------------------------------
# Manifest: raw dump + chunk index
# ----------------------------------------------------------------------


def _sidecar_index(
    dump_dir: str, bin_name: str, chunk_bytes: int
) -> Optional[Dict]:
    """The precomputed chunk index dump_raw_params publishes next to the
    bin — spares the origin a full re-read + sha256 of a multi-GB bin on
    every version bump. None when absent or built with a different chunk
    size (then the caller hashes the bin itself)."""
    from areal_tpu.system.weight_transfer import chunk_sidecar_name

    try:
        with open(os.path.join(dump_dir, chunk_sidecar_name(bin_name))) as f:
            idx = json.load(f)
    except (OSError, ValueError, json.JSONDecodeError):
        return None
    if (
        idx.get("schema") != CHUNK_SCHEMA
        or idx.get("chunk_bytes") != chunk_bytes
    ):
        return None
    return idx


def chunk_manifest_for_dump(
    dump_dir: str, chunk_bytes: int = DEFAULT_CHUNK_BYTES,
    wire: Optional[str] = None,
) -> Optional[Dict]:
    """Merge the dump's params.json with a content-hash chunk index.
    Returns None when no (complete, schema-matching) raw dump is present;
    retries once on the GC race (manifest read, bin unlinked, manifest
    replaced). The params.json read shares weight_transfer's reader so a
    future raw-dump schema bump is refused here exactly like the mmap
    path refuses it — not chunked and distributed with misread layout.

    ``wire="int8"`` returns the manifest of the quantized companion bin
    (``params-v{N}.int8.bin``, written by dump_raw_params when the
    ``weight_wire_dtype`` knob is armed): leaves carry the int8
    data/scale layout and servers dequantize at assembly. None when the
    dump was written without that wire."""
    from areal_tpu.system.weight_transfer import (
        _read_manifest,
        read_layout_sidecar,
        wire_bin_name,
    )

    for _ in range(2):
        man = _read_manifest(dump_dir)
        if man is None:
            return None
        version = int(man["version"])
        sharded = man.get("storage") == "sharded"
        if wire not in (None, "raw", "model"):
            if sharded:
                # Sharded dumps publish no quantized companion (wire
                # scales reduce an axis FSDP shards — see
                # dump_raw_params_sharded); only the raw wire exists.
                return None
            # Quantized wire: the companion bin's layout sidecar is the
            # source of truth for leaves AND total (params.json only
            # describes the raw bin).
            bin_name = wire_bin_name(version, wire)
            layout = read_layout_sidecar(dump_dir, bin_name)
            if layout is None or layout.get("wire") != wire:
                return None
            leaves = layout["leaves"]
            want_total = int(layout["total_bytes"])
        else:
            wire = None
            bin_name = man["bin"]
            leaves = man["leaves"]
            want_total = man.get("total_bytes")
        try:
            idx = _sidecar_index(dump_dir, bin_name, chunk_bytes)
            if idx is None and sharded:
                # No single bin to hash: stream the virtual full bin
                # through the slab reader once (the multi-process dump
                # can't publish a dump-time index — process 0 never sees
                # sibling slabs before its manifest lands) — then
                # PERSIST it as the sidecar, so an origin restart
                # (recover_mode relaunch) never re-sha256s a multi-GB
                # slab set before answering its first manifest.
                from areal_tpu.system.weight_transfer import (
                    _write_json_atomic,
                    chunk_sidecar_name,
                )

                idx = _index_from_reader(dump_dir, man, chunk_bytes)
                try:
                    _write_json_atomic(
                        dump_dir, chunk_sidecar_name(bin_name), idx
                    )
                except OSError:
                    pass  # read-only dump dir: stay lazy
            elif idx is None:
                idx = build_chunk_index(
                    os.path.join(dump_dir, bin_name), chunk_bytes
                )
        except FileNotFoundError:
            continue
        except (OSError, ValueError, KeyError):
            return None
        if idx["total_bytes"] != want_total:
            return None  # torn write (or a stale sidecar)
        out = {
            **idx,
            "version": version,
            "bin": bin_name,
            "wire": wire or "raw",
            # The FULL payload of this wire: the denominator for both
            # the origin's full_payload_equivalents and a sliced
            # fetcher's ingress fraction.
            "model_total_bytes": int(idx["total_bytes"]),
            "leaves": leaves,
        }
        if sharded:
            # Server-side hint only: readers fetch chunks of the same
            # virtual stream regardless of how the dump is stored.
            out["storage"] = "sharded"
            out["n_slabs"] = int(man.get("n_slabs", 1))
        return out
    return None


def _index_from_reader(dump_dir: str, manifest: Dict, chunk_bytes: int) -> Dict:
    """Chunk index of a sharded dump's virtual full stream, one read
    pass over the slabs (page-cache hot on the dump host)."""
    from areal_tpu.system.weight_transfer import (
        DumpStreamReader,
        chunk_index_from_reader,
    )

    with DumpStreamReader(dump_dir, manifest) as reader:
        return chunk_index_from_reader(
            reader, int(manifest["total_bytes"]), chunk_bytes
        )


def _leaf_segments(leaf: Dict, slices) -> List[Dict]:
    """shard_stream_plan segments for one layout leaf: the sliced data
    slab, plus the sliced scale slab for int8-wire leaves (scales reduce
    the quantization axis -2, so their slices drop that entry)."""
    seg = {
        "path": leaf["path"], "kind": "data", "offset": int(leaf["offset"]),
        "shape": list(leaf["shape"]), "nbytes": int(leaf["nbytes"]),
        "slices": [list(s) for s in slices],
    }
    if leaf.get("wire", "raw") == "raw":
        return [seg]
    scale_slices = [list(s) for s in slices]
    del scale_slices[-2]
    return [
        seg,
        {
            "path": leaf["path"], "kind": "scales",
            "offset": int(leaf["scale_offset"]),
            "shape": list(leaf["scale_shape"]),
            "nbytes": int(leaf["scale_nbytes"]),
            "slices": scale_slices,
        },
    ]


def _leaves_with_nbytes(leaves: List[Dict]) -> List[Dict]:
    """Layout leaves with nbytes filled in (pre-sidecar dumps recorded
    only dtype/shape/offset in params.json)."""
    out = []
    for e in leaves:
        if "nbytes" in e:
            out.append(e)
            continue
        import ml_dtypes  # noqa: F401  registers bfloat16 by name
        import numpy as np

        n = int(np.prod(e["shape"], dtype=np.int64)) if e["shape"] else 1
        out.append(dict(e, nbytes=n * np.dtype(e["dtype"]).itemsize))
    return out


def shard_manifest_from_full(
    man: Dict, tp_degree: int, tp_rank: int,
    ep_degree: int = 1, ep_rank: int = 0,
) -> Tuple[Dict, List[Tuple[int, int]]]:
    """Slice a full (per-wire) manifest into one tensor-parallel rank's
    shard manifest plus the bin gather ranges its chunk stream reads.

    The shard's payload is a VIRTUAL stream: each leaf's shard slab
    (and, for int8-wire leaves, its sliced scales) concatenated in leaf
    order. It gets its OWN chunk grid and content hashes (filled in by
    the caller, which streams the ranges once), so sliced transfer keeps
    the full hash-authority discipline — sub-chunk Range reads against
    the raw bin's hashes could never verify. Slices come from
    parallel/sharding.py partition specs, i.e. exactly what the engine's
    NamedSharding will place; replicated leaves (norms, biases) appear
    in every rank's stream — the small +epsilon over payload/degree."""
    from areal_tpu.parallel.sharding import (
        compose_shard_slices, expert_shard_slices, tensor_shard_slices,
    )

    segments = []
    for leaf in _leaves_with_nbytes(man["leaves"]):
        slices = tensor_shard_slices(
            leaf["path"], leaf["shape"], tp_degree, tp_rank
        )
        if ep_degree > 1:
            # (wire, ep_degree, ep_rank) streams additionally slice the
            # EXPERT dim of stacked MoE leaves (disjoint from the TP
            # dim, so the two compose): the rank fetches only its own
            # experts and ingress scales ~1/EP for expert-dominated
            # checkpoints.
            slices = compose_shard_slices(
                slices,
                expert_shard_slices(
                    leaf["path"], leaf["shape"], ep_degree, ep_rank
                ),
                leaf["shape"],
            )
        segments.extend(_leaf_segments(leaf, slices))
    plan = shard_stream_plan(segments)
    by_path: Dict[str, Dict] = {}
    order: List[str] = []
    for seg in plan["segments"]:
        path = seg["path"]
        if path not in by_path:
            order.append(path)
        if seg["kind"] == "data":
            by_path[path] = {
                "path": path, "shape": seg["local_shape"],
                "global_shape": seg["shape"], "slices": seg["slices"],
                "offset": seg["local_offset"], "nbytes": seg["local_nbytes"],
            }
        else:
            by_path[path].update(
                scale_offset=seg["local_offset"],
                scale_nbytes=seg["local_nbytes"],
                scale_shape=seg["local_shape"],
            )
    for leaf in man["leaves"]:
        e = by_path[leaf["path"]]
        e["dtype"] = leaf["dtype"]
        e["wire"] = leaf.get("wire", "raw")
    shard_man = {
        "schema": CHUNK_SCHEMA,
        "version": int(man["version"]),
        "bin": man["bin"],
        "wire": man.get("wire", "raw"),
        "shard": (
            {"tp_degree": int(tp_degree), "tp_rank": int(tp_rank)}
            if ep_degree <= 1 else
            {"tp_degree": int(tp_degree), "tp_rank": int(tp_rank),
             "ep_degree": int(ep_degree), "ep_rank": int(ep_rank)}
        ),
        "chunk_bytes": int(man["chunk_bytes"]),
        "total_bytes": int(plan["total_bytes"]),
        "n_chunks": len(chunk_spans(plan["total_bytes"], man["chunk_bytes"])),
        "model_total_bytes": int(
            man.get("model_total_bytes", man["total_bytes"])
        ),
        "hashes": [],  # caller fills from one pass over the ranges
        "leaves": [by_path[p] for p in order],
    }
    return shard_man, plan["ranges"]


def manifest_stream_key(man_or_query: Dict) -> Tuple[str, int, int, int, int]:
    """(wire, tp_degree, tp_rank, ep_degree, ep_rank) identity of a
    chunk stream — the key holders match requests against (a rank-0
    peer must not serve rank-1 chunk indices: same version, different
    bytes; likewise an EP-sliced stream vs a TP-sliced one)."""
    wire = man_or_query.get("wire") or "raw"
    shard = man_or_query.get("shard") or {}
    degree = int(man_or_query.get("tp_degree") or shard.get("tp_degree") or 1)
    rank = int(man_or_query.get("tp_rank") or shard.get("tp_rank") or 0)
    ep_degree = int(
        man_or_query.get("ep_degree") or shard.get("ep_degree") or 1
    )
    ep_rank = int(man_or_query.get("ep_rank") or shard.get("ep_rank") or 0)
    return (str(wire), degree, rank, ep_degree, ep_rank)


# ----------------------------------------------------------------------
# Shared HTTP surface (origin + peers speak the same contract)
# ----------------------------------------------------------------------


def parse_range_start(request: web.Request) -> int:
    """``Range: bytes=<start>-`` -> start (0 when absent/malformed):
    the resume offset for a torn chunk download."""
    rng = request.headers.get("Range", "")
    if rng.startswith("bytes=") and rng.endswith("-"):
        try:
            return max(0, int(rng[len("bytes="):-1]))
        except ValueError:
            return 0
    return 0


def chunk_response(data: memoryview, start: int, chunk_hash: str) -> web.Response:
    if start >= len(data):
        return web.json_response({"error": "range start past chunk"}, status=416)
    # Chaos point (corrupt action): flip payload bytes AFTER the hash
    # header was stamped — every consumer's sha256 verify must catch it
    # and re-fetch; corrupt weights must never cut over silently.
    body = faults.maybe_corrupt("weight_plane.chunk_bytes", bytes(data[start:]))
    return web.Response(
        body=body,
        status=206 if start else 200,
        headers={
            "X-Chunk-Hash": chunk_hash,
            "X-Chunk-Bytes": str(len(data)),
        },
        content_type="application/octet-stream",
    )


def _store_matches_query(store, query) -> bool:
    """A holder serves exactly ONE chunk stream: its manifest's
    (wire, shard) identity. A request for a different stream — or with
    an unparseable one — 404s (the fetcher falls through to a
    same-shard peer or the origin); rank-0 bytes must never satisfy
    rank-1 chunk indices."""
    try:
        want = manifest_stream_key(dict(query))
    except ValueError:
        return False
    return manifest_stream_key(store.manifest) == want


def serve_store_manifest(store, request: web.Request) -> web.Response:
    """Shared /weights/manifest contract for ChunkStore holders
    (PeerStoreServer and the generation server's mounted handler)."""
    want = request.query.get("version")
    try:
        want_v = int(want) if want is not None else None
    except ValueError:
        return web.json_response({"error": "bad version"}, status=400)
    if store is None or (want_v is not None and store.version != want_v):
        return web.json_response({"error": "not holding"}, status=404)
    if not _store_matches_query(store, request.query):
        return web.json_response(
            {"error": "holding a different chunk stream"}, status=404
        )
    return web.json_response(store.manifest)


def serve_store_chunk(
    store, request: web.Request
) -> Tuple[web.Response, int]:
    """Shared /weights/chunk contract for ChunkStore holders. Returns
    ``(response, bytes_served)`` so each caller keeps its own egress
    bookkeeping. A fetching holder 404s chunks it hasn't verified yet;
    the child retries or falls back to the next upstream."""
    try:
        version = int(request.query["version"])
        idx = int(request.query["idx"])
    except (KeyError, ValueError):
        return (
            web.json_response({"error": "version/idx required"}, status=400),
            0,
        )
    if (
        store is None
        or store.version != version
        or not _store_matches_query(store, request.query)
        or not store.has(idx)
    ):
        return web.json_response({"error": "chunk not held"}, status=404), 0
    data = store.chunk(idx)
    start = parse_range_start(request)
    return (
        chunk_response(data, start, store.manifest["hashes"][idx]),
        max(0, len(data) - start),
    )


class _PlaneHTTP:
    """Own-thread aiohttp server shared by the origin and peer holders."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._host, self._port = host, port
        self._loop = asyncio.new_event_loop()
        self._ready = threading.Event()
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self.address: str = ""

    def routes(self, app: web.Application):
        raise NotImplementedError

    def start(self):
        self._thread.start()
        if not self._ready.wait(30):
            raise RuntimeError("weight-plane HTTP failed to start")
        return self

    def _serve(self):
        asyncio.set_event_loop(self._loop)
        app = web.Application()
        self.routes(app)
        runner = web.AppRunner(app)
        self._loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, self._host, self._port)
        self._loop.run_until_complete(site.start())
        port = site._server.sockets[0].getsockname()[1]
        self.address = f"http://{self._host}:{port}"
        self._ready.set()
        try:
            self._loop.run_forever()
        finally:
            # close() stopped the loop: release the listening socket and
            # the loop's fds here, in the owning thread — holders are
            # created per fanout, so leaking them accumulates.
            try:
                self._loop.run_until_complete(runner.cleanup())
            except Exception:
                pass
            self._loop.close()

    def close(self):
        try:
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._thread.join(timeout=5)
        except Exception:
            pass


class WeightPlaneSource(_PlaneHTTP):
    """Trainer-side origin: serves the raw-bin dump dir over chunked
    HTTP. Lazily (re)builds the chunk index per version and counts every
    byte it egresses — the fleet's O(1)-origin-payload property is
    asserted straight off these counters."""

    def __init__(
        self,
        dump_dir: str,
        chunk_bytes: int = DEFAULT_CHUNK_BYTES,
        host: str = "127.0.0.1",
    ):
        super().__init__(host=host)
        self.dump_dir = dump_dir
        self.chunk_bytes = chunk_bytes
        # Cached full manifests, one per wire ("raw" / "int8").
        self._man: Dict[str, Optional[Dict]] = {}
        # Cached shard streams: (version, wire, degree, rank) ->
        # (manifest-with-hashes, bin gather ranges). Building one costs
        # a single pass over the shard's bytes (slice + sha256); pruned
        # to the two GC-live versions.
        self._shards: Dict[Tuple[int, str, int, int], Tuple[Dict, List]] = {}
        # Cached stream readers per (version, wire): os.pread-based, so
        # one reader serves concurrent chunk requests; for SHARDED dumps
        # the reader gathers the virtual full stream from per-process
        # slab files (the origin never materializes the whole model).
        # Pruned readers RETIRE with a grace period instead of closing
        # immediately: an executor thread may still hold one mid-pread
        # (closing its fds under it would 404 a servable chunk — or,
        # after fd reuse, read the wrong file; the client's hash verify
        # is the backstop, not the plan).
        self._readers: Dict[Tuple[int, str], Any] = {}
        self._retired_readers: List[Tuple[float, Any]] = []
        self._lock = threading.Lock()
        # Serializes manifest (re)builds WITHOUT blocking chunk serving:
        # a rebuild may sha256 the whole bin (sidecar missing), and
        # holding self._lock for that would stall every concurrent
        # _read_chunk counter update and stats() call.
        self._build_lock = threading.Lock()
        # Per-version egress counters (monotonic; survive re-dumps).
        self.chunks_served: Dict[int, int] = {}
        self.bytes_served: Dict[int, int] = {}
        # Egress + full-payload size per (version, wire): the O(1)-origin
        # invariant divides each wire's egress by ITS OWN full payload
        # (an int8 stream is ~half the raw bytes; shard streams sum to
        # ~one full payload per TP group), then sums the wires.
        self._bytes_by_wire: Dict[Tuple[int, str], int] = {}
        self._full_by_wire: Dict[Tuple[int, str], int] = {}

    def routes(self, app: web.Application):
        app.router.add_get("/weights/manifest", self._h_manifest)
        app.router.add_get("/weights/chunk", self._h_chunk)
        app.router.add_get("/weights/stats", self._h_stats)

    def register(self, experiment_name: str, trial_name: str, model_name: str):
        """Publish this origin's URL for manager discovery."""
        from areal_tpu.base import name_resolve, names

        name_resolve.add(
            names.weight_plane_source(experiment_name, trial_name, model_name),
            self.address,
            keepalive_ttl=60,
            replace=True,
        )
        return self

    def _dump_version(self) -> Optional[int]:
        """The dump dir's CURRENT version, off the (tiny) params.json
        alone — no bin hashing."""
        try:
            with open(os.path.join(self.dump_dir, _MANIFEST)) as f:
                return int(json.load(f)["version"])
        except (OSError, ValueError, KeyError, json.JSONDecodeError):
            return None

    def _cached_manifest(
        self, want_version: Optional[int], wire: str
    ) -> Optional[Dict]:
        """The cached chunk manifest, or None when it can't serve this
        request (absent, wrong pinned version, or — for an unpinned
        request, which wants the LATEST dump — lagging a newer version
        whose predecessor's bin may already be GC'd)."""
        with self._lock:
            man = self._man.get(wire)
        if man is None:
            return None
        if want_version is None:
            cur = self._dump_version()
            if cur is not None and cur != man["version"]:
                return None
            return man
        return man if man["version"] == want_version else None

    def _manifest(
        self, want_version: Optional[int], wire: str = "raw"
    ) -> Optional[Dict]:
        man = self._cached_manifest(want_version, wire)
        if man is not None:
            return man
        # A rebuild may hash the full bin (sidecar missing). Check the
        # (tiny) dump manifest first: requests pinned to a version this
        # dir does NOT hold (e.g. retries for v N after v N+1 landed)
        # must 404 cheaply, not re-hash per attempt.
        if want_version is not None and self._dump_version() != want_version:
            return None
        with self._build_lock:
            # built while we waited
            man = self._cached_manifest(want_version, wire)
            if man is None:
                man = chunk_manifest_for_dump(
                    self.dump_dir, self.chunk_bytes,
                    wire=None if wire == "raw" else wire,
                )
                if man is not None:
                    with self._lock:
                        self._man[wire] = man
        if man is None:
            return None
        if want_version is not None and man["version"] != want_version:
            return None
        return man

    def _get_reader(self, man: Dict):
        """The (cached) stream reader for one manifest's payload, or
        None when its bin/slabs vanished (GC race: caller 404s). Old
        versions' readers are pruned (their fds pin unlinked files)."""
        from areal_tpu.system.weight_transfer import DumpStreamReader

        version = int(man["version"])
        key = (version, man.get("wire", "raw"))
        with self._lock:
            r = self._readers.get(key)
        if r is not None:
            return r
        # Wire companions have no storage tag: they are contiguous bins
        # described by their own layout; pass the manifest straight in.
        try:
            r = DumpStreamReader(self.dump_dir, man)
        except (OSError, ValueError, KeyError):
            return None
        now = time.monotonic()
        with self._lock:
            have = self._readers.get(key)
            if have is not None:
                r.close()
                return have
            for k in [k for k in self._readers if k[0] < version - 1]:
                self._retired_readers.append((now, self._readers.pop(k)))
            self._readers[key] = r
            closable = [
                old for t, old in self._retired_readers if now - t > 120.0
            ]
            self._retired_readers = [
                (t, old) for t, old in self._retired_readers
                if now - t <= 120.0
            ]
        for old in closable:
            old.close()
        return r

    def close(self):
        super().close()
        with self._lock:
            readers = list(self._readers.values()) + [
                r for _, r in self._retired_readers
            ]
            self._readers = {}
            self._retired_readers = []
        for r in readers:
            r.close()

    def _shard_stream(
        self, want_version: Optional[int], wire: str, degree: int, rank: int,
        ep_degree: int = 1, ep_rank: int = 0,
    ) -> Optional[Tuple[Dict, List, List]]:
        """(shard manifest, bin gather ranges, stream prefix sums) for
        one TP/EP rank's sliced stream, built (one slice+hash pass over
        the shard's bytes) and cached per (version, wire, degree, rank,
        ep_degree, ep_rank)."""
        full = self._manifest(want_version, wire)
        if full is None:
            return None
        version = int(full["version"])
        key = (
            version, wire, int(degree), int(rank),
            int(ep_degree), int(ep_rank),
        )
        with self._lock:
            hit = self._shards.get(key)
        if hit is not None:
            return hit
        with self._build_lock:
            with self._lock:
                hit = self._shards.get(key)
            if hit is not None:
                return hit
            try:
                man, ranges = shard_manifest_from_full(
                    full, degree, rank, ep_degree=ep_degree, ep_rank=ep_rank
                )
            except (ValueError, KeyError) as e:
                logger.warning(
                    f"shard manifest v{version} {wire} tp {rank}/{degree} "
                    f"ep {ep_rank}/{ep_degree} failed: {e!r}"
                )
                return None
            chunker = StreamChunker(man["chunk_bytes"])
            reader = self._get_reader(full)
            if reader is None:
                return None
            try:
                for off, length in ranges:
                    pos = 0
                    while pos < length:
                        n = min(4 << 20, length - pos)
                        chunker.update(reader.read_at(off + pos, n))
                        pos += n
            except (OSError, ValueError):
                return None
            idx = chunker.finish()
            if idx["total_bytes"] != man["total_bytes"]:
                return None
            man["hashes"] = idx["hashes"]
            entry = (man, ranges, stream_prefix(ranges))
            with self._lock:
                # Prune streams for GC'd versions (keep the two live).
                for k in [k for k in self._shards if k[0] < version - 1]:
                    del self._shards[k]
                self._shards[key] = entry
        return entry

    @staticmethod
    def _parse_stream_query(
        query,
    ) -> Tuple[Optional[int], str, int, int, int, int]:
        want = query.get("version")
        want_v = int(want) if want is not None else None
        wire = query.get("wire") or "raw"
        degree = int(query.get("tp_degree") or 1)
        rank = int(query.get("tp_rank") or 0)
        if degree < 1 or not (0 <= rank < degree):
            raise ValueError(f"bad shard {rank}/{degree}")
        ep_degree = int(query.get("ep_degree") or 1)
        ep_rank = int(query.get("ep_rank") or 0)
        if ep_degree < 1 or not (0 <= ep_rank < ep_degree):
            raise ValueError(f"bad expert shard {ep_rank}/{ep_degree}")
        return want_v, wire, degree, rank, ep_degree, ep_rank

    async def _h_manifest(self, request: web.Request) -> web.Response:
        try:
            (want_v, wire, degree, rank,
             ep_degree, ep_rank) = self._parse_stream_query(request.query)
        except ValueError:
            return web.json_response({"error": "bad stream query"}, status=400)
        # A cache miss sha256-hashes the whole bin / shard stream
        # (build_chunk_index): off the event loop, so pending chunk
        # requests keep flowing.
        if degree > 1 or ep_degree > 1:
            got = await asyncio.get_running_loop().run_in_executor(
                None, self._shard_stream, want_v, wire, degree, rank,
                ep_degree, ep_rank,
            )
            man = got[0] if got else None
        else:
            man = await asyncio.get_running_loop().run_in_executor(
                None, self._manifest, want_v, wire
            )
        if man is None:
            return web.json_response(
                {"error": "no dump for requested stream", "retry_after": 0.2},
                status=404,
            )
        return web.json_response(man)

    def _count_egress(
        self, version: int, wire: str, full_bytes: int, served: int
    ) -> None:
        with self._lock:
            self.chunks_served[version] = (
                self.chunks_served.get(version, 0) + 1
            )
            self.bytes_served[version] = (
                self.bytes_served.get(version, 0) + served
            )
            self._bytes_by_wire[(version, wire)] = (
                self._bytes_by_wire.get((version, wire), 0) + served
            )
            self._full_by_wire[(version, wire)] = full_bytes

    def _read_chunk(
        self, version: int, idx: int, start: int,
        wire: str, degree: int, rank: int,
        ep_degree: int = 1, ep_rank: int = 0,
    ) -> web.Response:
        """Blocking part of /weights/chunk (manifest build + pread),
        run on an executor thread."""
        if degree > 1 or ep_degree > 1:
            got = self._shard_stream(
                version, wire, degree, rank, ep_degree, ep_rank
            )
            if got is None:
                return web.json_response({"error": "unknown stream"}, status=404)
            man, ranges, prefix = got
            if not (0 <= idx < man["n_chunks"]):
                return web.json_response({"error": "unknown chunk"}, status=404)
            # Shard manifests slice the FULL stream: reader keyed off
            # the full manifest (its bin), not the virtual shard stream.
            full = self._manifest(version, wire)
            reader = self._get_reader(full) if full is not None else None
            if reader is None:
                return web.json_response(
                    {"error": "bin vanished (GC race)"}, status=404
                )
            off = idx * man["chunk_bytes"]
            length = min(man["chunk_bytes"], man["total_bytes"] - off)
            try:
                data = gather_stream(
                    reader.read_at, ranges, off, length, prefix=prefix
                )
            except (OSError, ValueError):
                return web.json_response(
                    {"error": "bin vanished (GC race)"}, status=404
                )
        else:
            man = self._manifest(version, wire)
            if man is None or not (0 <= idx < man["n_chunks"]):
                return web.json_response({"error": "unknown chunk"}, status=404)
            off = idx * man["chunk_bytes"]
            length = min(man["chunk_bytes"], man["total_bytes"] - off)
            # One pread per request off the page cache; the bin (or its
            # slab files, for a sharded trainer dump) is mmap-hot on the
            # dump host already.
            reader = self._get_reader(man)
            if reader is None:
                return web.json_response(
                    {"error": "bin vanished (GC race)"}, status=404
                )
            try:
                data = reader.read_at(off, length)
            except (OSError, ValueError):
                return web.json_response({"error": "short read"}, status=404)
        self._count_egress(
            version, wire,
            int(man.get("model_total_bytes", man["total_bytes"])),
            max(0, length - start),
        )
        return chunk_response(memoryview(data), start, man["hashes"][idx])

    async def _h_chunk(self, request: web.Request) -> web.Response:
        await faults.maybe_fail_async("weight_plane.serve_chunk")
        try:
            version = int(request.query["version"])
            idx = int(request.query["idx"])
            (_, wire, degree, rank,
             ep_degree, ep_rank) = self._parse_stream_query(request.query)
        except (KeyError, ValueError):
            return web.json_response({"error": "version/idx required"}, status=400)
        return await asyncio.get_running_loop().run_in_executor(
            None, self._read_chunk, version, idx,
            parse_range_start(request), wire, degree, rank,
            ep_degree, ep_rank,
        )

    def stats(self) -> Dict:
        with self._lock:
            return {
                "chunks_served": dict(self.chunks_served),
                "bytes_served": dict(self.bytes_served),
                # Full-payload equivalents egressed per version: the
                # number the O(1)-origin assertion is written against.
                # Each (version, wire)'s egress divides by that wire's
                # OWN full payload (quantized streams are ~half the raw
                # bytes; a TP group's shard streams sum to ~one full
                # payload + the replicated-leaf epsilon), then wires
                # sum per version. Counters outlive the cached manifest.
                "full_payload_equivalents": {
                    v: sum(
                        (b / self._full_by_wire[(vv, w)]
                         if self._full_by_wire.get((vv, w)) else 0.0)
                        for (vv, w), b in self._bytes_by_wire.items()
                        if vv == v
                    )
                    for v in {vv for vv, _ in self._bytes_by_wire}
                } or {
                    v: 0.0 for v in self.bytes_served
                },
            }

    async def _h_stats(self, request: web.Request) -> web.Response:
        return web.json_response(self.stats())


class PeerStoreServer(_PlaneHTTP):
    """Serve a fetched ChunkStore over the same /weights contract (a
    'holder'). The bench workload builds its fanout fleet from these;
    generation servers mount equivalent handlers on their own app."""

    def __init__(self, host: str = "127.0.0.1"):
        super().__init__(host=host)
        self.store = None  # engine.weight_client.ChunkStore
        self.chunks_served = 0
        self.bytes_served = 0

    def routes(self, app: web.Application):
        app.router.add_get("/weights/manifest", self._h_manifest)
        app.router.add_get("/weights/chunk", self._h_chunk)

    async def _h_manifest(self, request: web.Request) -> web.Response:
        return serve_store_manifest(self.store, request)

    async def _h_chunk(self, request: web.Request) -> web.Response:
        await faults.maybe_fail_async("weight_plane.serve_chunk")
        # Off the loop (like the origin's _h_chunk): the copy is up to a
        # full chunk, and a holder fields one request per chunk per child.
        resp, served = await asyncio.get_running_loop().run_in_executor(
            None, serve_store_chunk, self.store, request
        )
        if served:
            self.chunks_served += 1
            self.bytes_served += served
        return resp


# ----------------------------------------------------------------------
# Fanout planning
# ----------------------------------------------------------------------


def plan_fanout(
    origin_url: str, server_urls: List[str], degree: int
) -> List[List[Tuple[str, str]]]:
    """Degree-bounded distribution tree as BFS waves.

    Returns ``[[(server_url, parent_url), ...], ...]`` — wave k's servers
    fetch from parents that completed in wave k-1 (wave 0's parent is the
    origin). With the canonical k-ary layout over the sorted server list,
    server i's parent is ``servers[i // degree - 1]`` (origin for
    ``i < degree``), so the origin uploads at most ``degree`` copies of
    each byte and every other hop is peer-to-peer."""
    if degree < 1:
        raise ValueError(f"fanout degree must be >= 1, got {degree}")
    servers = list(server_urls)
    waves: List[List[Tuple[str, str]]] = []
    level: Dict[str, int] = {}
    for i, u in enumerate(servers):
        parent = origin_url if i < degree else servers[i // degree - 1]
        lvl = 0 if i < degree else level[parent] + 1
        level[u] = lvl
        while len(waves) <= lvl:
            waves.append([])
        waves[lvl].append((u, parent))
    return waves


def fanout_edges(waves: List[List[Tuple[str, str]]]) -> List[Tuple[str, str]]:
    return [edge for wave in waves for edge in wave]


def group_by_shard(
    server_urls: List[str],
    shards: Dict[str, Optional[Tuple[int, int]]],
) -> Dict[Tuple[int, int], List[str]]:
    """Partition servers into same-shard peer groups: key is
    ``(tp_degree, tp_rank)`` (unsharded servers land in ``(1, 0)``).
    Only same-shard peers hold the same chunk stream, so the fanout
    tree — and mid-transfer re-parenting — is planned PER GROUP; the
    origin still uploads each shard's bytes once, so fleet-wide cost
    stays ~one full payload per version regardless of group count."""
    groups: Dict[Tuple[int, int], List[str]] = {}
    for u in server_urls:
        spec = shards.get(u)
        if spec is None:
            key = (1, 0)
        else:
            rank, degree = int(spec[0]), int(spec[1])
            if degree < 1 or not (0 <= rank < degree):
                raise ValueError(f"bad shard {rank}/{degree} for {u}")
            key = (degree, rank)
        groups.setdefault(key, []).append(u)
    return groups


# ----------------------------------------------------------------------
# Host-level convenience: run one fanout over plain holders (bench +
# chaos tests drive this; the gserver manager has its own async variant
# integrated with health/tracing).
# ----------------------------------------------------------------------


def distribute_to_stores(
    origin_url: str,
    n_holders: int,
    degree: int,
    version: Optional[int] = None,
    timeout: float = 30.0,
) -> Tuple[List[PeerStoreServer], Dict]:
    """Fetch one payload from `origin_url` into `n_holders` fresh
    PeerStoreServers along a degree-bounded tree, wave by wave. Returns
    (holders, stats). Caller owns holder shutdown."""
    from areal_tpu.engine.weight_client import ChunkStore, fetch_manifest

    man = fetch_manifest(origin_url, version=version, timeout=timeout)
    holders = [PeerStoreServer().start() for _ in range(n_holders)]
    by_url = {h.address: h for h in holders}
    waves = plan_fanout(origin_url, [h.address for h in holders], degree)
    t0 = time.monotonic()
    per_holder: Dict[str, Dict] = {}
    completed: List[str] = []
    for wave in waves:
        threads = []
        for url, parent in wave:
            holder = by_url[url]
            holder.store = ChunkStore(man)
            # Fallback order mirrors the gserver manager's: surviving
            # PEER holders before the origin, so a holder that dies
            # mid-chain re-fanouts from a sibling and origin egress
            # stays O(1) even under chaos.
            fallbacks = [u for u in completed if u != parent][:2]

            def run(h=holder, p=parent, fb=fallbacks):
                stats = h.store.fetch(
                    [p] + fb + [origin_url], origin=origin_url,
                    timeout=timeout,
                )
                per_holder[h.address] = stats

            t = threading.Thread(target=run, daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=timeout * max(1, man["n_chunks"]))
        completed.extend(u for u, _ in wave if u in per_holder)
    missing = [u for u, _ in fanout_edges(waves) if u not in per_holder]
    if missing:
        # This function owns the holders until it returns them: close
        # them on the failure path (each pins an event-loop thread, a
        # socket, and a payload-sized buffer).
        for h in holders:
            h.close()
        raise RuntimeError(f"fanout incomplete: {missing} never finished")
    return holders, {
        "version": man["version"],
        "total_bytes": man["total_bytes"],
        "n_chunks": man["n_chunks"],
        "wall_s": time.monotonic() - t0,
        "per_holder": per_holder,
    }

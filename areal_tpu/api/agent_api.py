"""Agent abstraction for rollout workers.

Counterpart of the reference agent API (realhf/api/core/agent_api.py:15).
An agent turns one prompt into trajectories by exchanging observations and
actions with the generation infrastructure through a pair of asyncio
queues: the agent puts (token_ids, gconfig) requests on `obs_queue` and
awaits `BundledGenerationOutputs` from `act_queue`.
"""

from __future__ import annotations

import abc
import asyncio
from typing import Any, List

from areal_tpu.api.config import AgentAbstraction, Registry
from areal_tpu.api.data_api import SequenceSample
from areal_tpu.api.env_api import EnvironmentService


class Agent(abc.ABC):

    @abc.abstractmethod
    async def collect_trajectory(
        self,
        prompt: SequenceSample,
        env: EnvironmentService,
        obs_queue: asyncio.Queue,
        act_queue: asyncio.Queue,
    ) -> List[SequenceSample]:
        """Run one episode; returns trajectories to push to the trainer."""


AGENT_REGISTRY = Registry("agent")


def register_agent(name: str, factory):
    AGENT_REGISTRY.register(name, factory)


def make_agent(cfg: AgentAbstraction | str, **kwargs) -> Agent:
    return AGENT_REGISTRY.make(cfg, **kwargs)

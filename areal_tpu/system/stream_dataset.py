"""Stream dataset: makes async rollouts look like a dataset to the trainer.

Counterpart of the reference's PullerStreamDataset
(realhf/system/stream_dataset.py:23-106): a background thread pulls JSON
trajectories from the rollout workers' push stream into a queue; the
model worker's "fetch" handler drains it into `SequenceSample` batches.

With AREAL_WAL armed (the default) every accepted trajectory journals to
an append-only WAL before its pusher is acked, and a restart replays the
journal — so trajectories that were in flight when the trainer died
survive the kill. A per-seq membership set drops redelivered duplicates
at admission (acking them immediately: they are already durable here).
"""

from __future__ import annotations

import os
import queue
import threading
from collections import deque
from typing import List, Optional

from areal_tpu.api import data_api
from areal_tpu.base import constants, env_registry, logging, tracing
from areal_tpu.system.push_pull_stream import NameResolvingZmqPuller
from areal_tpu.system.wal import RolloutWAL, SeqLedger

logger = logging.getLogger("stream_dataset")


class PullerStreamDataset:
    def __init__(
        self,
        experiment_name: str,
        trial_name: str,
        puller_index: int = 0,
        max_queue_size: int = 4096,
        pull_timeout_ms: int = 100,
    ):
        self.puller = NameResolvingZmqPuller(
            experiment_name, trial_name, puller_index=puller_index
        )
        self._queue: "queue.Queue" = queue.Queue(maxsize=max_queue_size)
        self._stop = threading.Event()
        self._pull_timeout_ms = pull_timeout_ms
        self.counters = {
            "areal:train_wal_replayed_total": 0,
            "areal:train_wal_dup_dropped_total": 0,
        }
        # Journal of accepted trajectories; replayed (into a side deque
        # poll_batch serves first — the main queue's maxsize could
        # deadlock a large replay before the pull thread starts) and
        # then kept open for append. _seen guards re-journaling a seq
        # the journal already holds (pusher redelivery races).
        self._wal: Optional[RolloutWAL] = None
        self._wal_lock = threading.Lock()
        self._seen: set = set()
        self._replayed: deque = deque()
        # Samples held back because their ids collided with an earlier
        # sample in the same poll_batch drain (epoch carryover: a tiny
        # dataset re-issues row ids faster than the trainer drains the
        # queue). gather() refuses duplicate ids, so collisions are
        # deferred to the next batch rather than poisoning this one.
        self._held: deque = deque()
        if env_registry.get_bool("AREAL_WAL"):
            path = os.path.join(
                constants.get_recover_path(experiment_name, trial_name),
                "wal", f"puller{puller_index}.wal",
            )
            self._wal = RolloutWAL(path)
            for rec in self._wal.replay():
                seq = rec.get("seq")
                if seq is None or seq in self._seen:
                    continue
                try:
                    sample = data_api.sample_from_json(rec["data"])
                except Exception:
                    logger.exception("bad WAL trajectory dropped on replay")
                    continue
                self._seen.add(seq)
                sample.metadata["wal_seq"] = [seq] * sample.bs
                self._replayed.append(sample)
                self.counters["areal:train_wal_replayed_total"] += 1
            if self._replayed:
                logger.info(
                    "WAL replay: %d in-flight trajectories survived restart",
                    len(self._replayed),
                )
        self._thread = threading.Thread(target=self._pull_worker, daemon=True)
        self._thread.start()
        self.n_pulled = 0

    def _pull_worker(self):
        while not self._stop.is_set():
            try:
                d = self.puller.pull(timeout_ms=self._pull_timeout_ms)
            except TimeoutError:
                # Idle: flush the batched WAL fsync so deferred acks
                # don't sit past the fsync window with no traffic.
                if self._wal is not None:
                    with self._wal_lock:
                        self._wal.maybe_sync(force=True)
                continue
            except Exception:
                logger.exception("puller error")
                continue
            seq = self.puller.last_seq
            ack_addr = self.puller.last_ack_addr
            if seq is not None and seq in self._seen:
                # Redelivered duplicate: the journal already holds this
                # seq durably, so ack right away and never re-admit —
                # each drop here is a prevented duplicate.
                self.counters["areal:train_wal_dup_dropped_total"] += 1
                if ack_addr:
                    self.puller.ack(seq, ack_addr)
                continue
            try:
                sample = data_api.sample_from_json(d)
            except Exception:
                logger.exception("bad trajectory json dropped")
                continue
            if self._wal is not None and seq is not None:
                self._seen.add(seq)
                sample.metadata["wal_seq"] = [seq] * sample.bs
                # Journal before ack; the ack itself is deferred to the
                # fsync that covers this record — acking earlier would
                # let a kill in between lose an acked sample.
                on_durable = None
                if ack_addr:
                    on_durable = (
                        lambda s=seq, a=ack_addr: self.puller.ack(s, a)
                    )
                with self._wal_lock:
                    self._wal.append({"seq": seq, "data": d},
                                     on_durable=on_durable)
            self.n_pulled += 1
            # Queue residency is traced per sample: span from arrival on
            # this host to the fetch that drains it, parented under the
            # rollout's episode span (trace ctx rides the sample
            # metadata; 0 when tracing is off — never allocated).
            recv_ns = tracing.now_ns() if tracing.enabled() else 0
            # Block (with stop checks) rather than drop: the manager already
            # counted this trajectory as submitted, so dropping it would
            # desync the staleness accounting. Blocking applies backpressure
            # through the ZMQ high-water mark to the rollout workers.
            while not self._stop.is_set():
                try:
                    self._queue.put((recv_ns, sample), timeout=1)
                    break
                except queue.Full:
                    continue

    def qsize(self) -> int:
        return self._queue.qsize() + len(self._replayed) + len(self._held)

    def poll_batch(self, max_samples: int = 64) -> Optional["data_api.SequenceSample"]:
        """Drain up to max_samples pulled trajectories into one batch
        (held-back collisions first, then WAL-replayed survivors).

        A sample whose ids repeat an earlier sample in the SAME drain is
        a later-epoch episode of the same dataset row; it is deferred to
        the next batch (gather refuses duplicate ids, and one fetch must
        never deliver two copies of an id anyway — the master's buffer
        and storage tracker key on ids)."""
        samples: List[data_api.SequenceSample] = []
        batch_ids: set = set()
        deferred: List[data_api.SequenceSample] = []

        def take(sample: "data_api.SequenceSample"):
            if batch_ids.intersection(sample.ids):
                deferred.append(sample)
                return
            batch_ids.update(sample.ids)
            samples.append(sample)

        while len(samples) < max_samples and self._held:
            take(self._held.popleft())
        while len(samples) < max_samples and self._replayed:
            take(self._replayed.popleft())
        while len(samples) < max_samples:
            try:
                recv_ns, sample = self._queue.get_nowait()
            except queue.Empty:
                break
            if tracing.enabled() and recv_ns:
                ctx = (sample.metadata.get("trace_ctx") or [None])[0]
                tracing.record_span(
                    "stream.recv", recv_ns,
                    ctx=tracing.extract(ctx),
                    qid=str(sample.ids[0]) if sample.ids else "",
                )
            take(sample)
        self._held.extend(deferred)
        if not samples:
            return None
        return data_api.SequenceSample.gather(samples)

    def compact_wal(self, consumed: SeqLedger) -> int:
        """Checkpoint-barrier truncation: drop journaled records whose
        seqs the durable ledger marked consumed (they can never be
        needed by a future resume). Returns the number dropped."""
        if self._wal is None:
            return 0
        with self._wal_lock:
            return self._wal.compact(lambda rec: rec.get("seq") not in consumed)

    def __len__(self):
        # Unknown a priori; reference returns the configured dataset size.
        return self.qsize()

    def close(self):
        self._stop.set()
        self._thread.join(timeout=3)
        if self._wal is not None:
            with self._wal_lock:
                self._wal.close()
        self.puller.close()

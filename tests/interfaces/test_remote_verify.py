"""Remote verifier client: batching, retries, failure handling
(reference functioncall/base/call.py behaviors)."""

import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from areal_tpu.functioncall import remote


class StubVerifier(BaseHTTPRequestHandler):
    fail_first = 0  # class-level: number of requests to 500 first
    seen_batches = []

    def do_POST(self):
        cls = type(self)
        body = json.loads(self.rfile.read(int(self.headers["Content-Length"])))
        cls.seen_batches.append(body)
        if cls.fail_first > 0:
            cls.fail_first -= 1
            self.send_response(500)
            self.end_headers()
            return
        out = [
            {"uid": p["uid"], "success": p["solution"] == "good"} for p in body
        ]
        payload = json.dumps(out).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def log_message(self, *a):
        pass


@pytest.fixture()
def stub_server():
    StubVerifier.fail_first = 0
    StubVerifier.seen_batches = []
    srv = HTTPServer(("127.0.0.1", 0), StubVerifier)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_port}"
    srv.shutdown()


def test_batch_verify_order_and_batching(stub_server):
    payloads = [
        {"solution": "good" if i % 3 else "bad"} for i in range(10)
    ]
    res = remote.batch_verify(payloads, "math", domain=stub_server)
    assert res == [bool(i % 3) for i in range(10)]


def test_batch_verify_splits_batches(stub_server, monkeypatch):
    monkeypatch.setattr(remote, "DEFAULT_BATCH_SIZE", 4)
    payloads = [{"solution": "good"} for _ in range(10)]
    import asyncio
    res = asyncio.run(
        remote.batch_verify_async(
            payloads, "math", domain=stub_server, batch_size=4
        )
    )
    assert res == [True] * 10
    assert len(StubVerifier.seen_batches) == 3  # 4 + 4 + 2


def test_batch_verify_retries_on_500(stub_server, monkeypatch):
    monkeypatch.setattr(remote, "INITIAL_RETRY_S", 0.01)
    StubVerifier.fail_first = 1
    res = remote.batch_verify(
        [{"solution": "good"}], "math", domain=stub_server
    )
    assert res == [True]
    assert len(StubVerifier.seen_batches) == 2  # the 500 + the retry


def test_unreachable_service_scores_false(monkeypatch):
    monkeypatch.setattr(remote, "INITIAL_RETRY_S", 0.01)
    monkeypatch.setattr(remote, "MAX_RETRIES", 1)
    res = remote.batch_verify(
        [{"solution": "x"}], "math",
        domain="http://127.0.0.1:1", timeout_s=1.0,
    )
    assert res == [False]


def test_env_switch(monkeypatch):
    monkeypatch.delenv(remote.ENV_DOMAIN, raising=False)
    assert not remote.remote_enabled()
    monkeypatch.setenv(remote.ENV_DOMAIN, "http://x")
    assert remote.remote_enabled()


def test_reward_interface_uses_remote_when_enabled(stub_server, monkeypatch):
    """MultiTaskRewardInterface._verify_all dispatches to the remote
    service when FUNCTIONCALL_SERVICE_DOMAIN is set."""
    from areal_tpu.interfaces.reward import MultiTaskRewardInterface

    monkeypatch.setenv(remote.ENV_DOMAIN, stub_server)
    iface = MultiTaskRewardInterface()
    oks = iface._verify_all(
        [("math", "good", "1"), ("math", "bad", "2"), ("code", "good", "[]")]
    )
    assert oks == [True, False, True]
    # one batch per task family
    assert len(StubVerifier.seen_batches) == 2

"""Multi-turn tool-use e2e (ISSUE 18 acceptance): 2 real generation
servers + real gserver manager + a REAL pooled reward-executor fleet +
rollout worker running ToolUseAgent episodes + stream-dataset trainer,
with the executor-death chaos arm live — executor 0 is armed to `die`
on its first submit, so every episode's tool traffic must fail over to
the survivor and still finish (zero failed episodes)."""

import json
import urllib.request
import uuid

import pytest

from areal_tpu.api.config import (
    AgentAbstraction,
    DatasetAbstraction,
    EnvServiceAbstraction,
    ModelAbstraction,
)
from areal_tpu.api.system_api import (
    ExperimentConfig,
    GenerationServerConfig,
    GserverManagerConfig,
    RolloutWorkerConfig,
)
from areal_tpu.base import name_resolve, names
from areal_tpu.system.controller import LocalController
from tests import fixtures
from tests.system.test_async_e2e import (
    N_SEQS,
    _assert_continuation_reprefill,
    _deflaked_env,
    _trainer_parts,
)
from tests.system.test_e2e_experiments import _mk_tokenizer_files
from tests.system.test_reward_executor import _spawn_executor

pytestmark = pytest.mark.serial


def _wait_executor_urls(exp, trial, n, timeout=60.0):
    import time

    deadline = time.monotonic() + timeout
    urls = {}
    while len(urls) < n and time.monotonic() < deadline:
        for i in range(n):
            try:
                urls[i] = name_resolve.get(
                    names.reward_executor_url(exp, trial, str(i))
                )
            except name_resolve.NameEntryNotFoundError:
                pass
        time.sleep(0.2)
    assert len(urls) == n, f"only {sorted(urls)} of {n} executors registered"
    return urls


def _rexec_metrics(url):
    with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
        text = r.read().decode()
    return dict(
        (k, float(v))
        for k, v in (line.split() for line in text.splitlines() if line)
    )


@pytest.mark.slow
def test_tool_use_e2e(tmp_path, monkeypatch):
    exp, trial = f"e2e-tool-{uuid.uuid4().hex[:6]}", "t0"
    rows, tok_dir = _mk_tokenizer_files(tmp_path)
    mc_rows = [
        r for r in fixtures.make_math_code_rows(16, seed=13)
        if r["task"] == "math"
    ]
    data_path = fixtures.write_jsonl(mc_rows, tmp_path / "mc.jsonl")
    nr_root = str(tmp_path / "name_resolve")

    worker_env = _deflaked_env(tmp_path, monkeypatch)
    # Split admission windows live in the buffer for this run (math
    # tight, agentic loose) — the master's per-task staleness scalars
    # asserted below prove the task tag flowed rollout -> buffer ->
    # train batch.
    worker_env["AREAL_TASK_STALENESS_WINDOWS"] = "math:2,agentic:8"

    # The REAL executor fleet, as subprocesses on the shared
    # name_resolve root. Executor 0 carries the chaos arm: `rexec.die`
    # fires on its FIRST submit and os._exit()s the whole service.
    name_resolve.reconfigure("nfs", record_root=nr_root)
    procs = [
        _spawn_executor(
            0, exp, trial, nr_root, {"AREAL_FAULTS": "rexec.die=die"}
        ),
        _spawn_executor(1, exp, trial, nr_root),
    ]

    model_args, mw, master = _trainer_parts(exp, trial, tok_dir)
    gen_servers = [
        GenerationServerConfig(
            experiment_name=exp,
            trial_name=trial,
            server_index=i,
            model=ModelAbstraction("tpu_transformer", args=model_args),
            tokenizer_path=tok_dir,
            max_concurrent_requests=4,
            max_seq_len=256,
            decode_block_steps=4,
            # Turn continuations re-enter on sticky-qid routes; the
            # prefix cache is what makes the re-prefill delta real.
            prefix_cache_tokens=2048,
        )
        for i in range(2)
    ]
    gserver_mgr = GserverManagerConfig(
        experiment_name=exp,
        trial_name=trial,
        model_name="actor",
        n_servers=2,
        train_batch_size=N_SEQS,
        max_head_offpolicyness=100,  # don't gate in this tiny test
    )
    rollout = RolloutWorkerConfig(
        experiment_name=exp,
        trial_name=trial,
        worker_index=0,
        n_rollout_workers=1,
        n_pullers=1,
        agent=AgentAbstraction(
            "tool-use",
            args=dict(
                gconfig=dict(max_new_tokens=8),
                num_turns=3,
                # Tiny random models never emit tool syntax; script the
                # first two turns so every episode exercises the
                # executor pool + continuation path deterministically.
                scripted_tool_turns=2,
            ),
        ),
        env=EnvServiceAbstraction("tool-use"),
        datasets=[
            DatasetAbstraction(
                "math_code_prompt", args=dict(dataset_path=data_path)
            )
        ],
        tokenizer_path=tok_dir,
        # The ISSUE acceptance shape: 4 concurrent 3-turn episodes.
        max_concurrent_rollouts=4,
    )
    cfg = ExperimentConfig(
        experiment_name=exp,
        trial_name=trial,
        master=master,
        model_workers=[mw],
        rollout_workers=[rollout],
        gserver_manager=gserver_mgr,
        generation_servers=gen_servers,
    )
    ctl = LocalController(
        cfg,
        name_resolve_cfg={"backend": "nfs", "record_root": nr_root},
        worker_env=worker_env,
    )
    try:
        urls = _wait_executor_urls(exp, trial, 2)
        result = ctl.run()
        assert result["global_step"] == 2

        # Turns 2+ rode the session-continuation path with a re-prefill
        # strictly below the session-blind counterfactual.
        _assert_continuation_reprefill(tmp_path)

        # Episode telemetry surfaced as master scalars: every trained
        # episode ran its full 3 turns (zero failed/truncated episodes)
        # and both scripted tool calls executed.
        overlap = result["perf_summary"]["overlap"]
        assert overlap.get("episode_turns") == 3.0, overlap
        assert overlap.get("episode_tool_calls") == 2.0, overlap
        # Per-task staleness: the agentic tag survived rollout ->
        # buffer admission -> train batch -> master scalar.
        assert "task_staleness_agentic" in overlap, overlap

        # The chaos arm engaged: executor 0 died on its first submit...
        assert procs[0].wait(timeout=30) is not None
        # ...and the survivor absorbed the fleet's tool traffic.
        assert procs[1].poll() is None
        m = _rexec_metrics(urls[1])
        assert m["areal:rexec_jobs_total"] >= 1, m
        assert m["areal:rexec_workers_alive"] >= 1, m
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        from areal_tpu.base import tracing

        tracing.reconfigure()

"""NullAgent: generation-only trajectories, no env/verify calls
(reference realhf/impl/agent/null_agent.py)."""

import asyncio

import numpy as np

import areal_tpu.agents  # noqa: F401  (registers)
from areal_tpu.agents.null import NullAgent
from areal_tpu.api.agent_api import make_agent
from areal_tpu.api.config import AgentAbstraction
from areal_tpu.api.data_api import SequenceSample
from areal_tpu.api.model_api import BundledGenerationOutputs


def _prompt(qid="q0"):
    return SequenceSample.from_default(
        ids=[qid], seqlens=[3], data={"packed_prompts": np.array([5, 6, 7])},
    )


def _bundle():
    seqs = [[5, 6, 7, 11, 12], [5, 6, 7, 13, 14, 15]]
    return BundledGenerationOutputs(
        qid="q0",
        prompt_ids=[5, 6, 7],
        seqs=seqs,
        logprobs=[[0.0] * len(s) for s in seqs],
        no_eos=[True, False],
        version_start=[3, 3],
        version_end=[3, 3],
    )


def test_null_agent_multi_episode():
    agent = NullAgent(max_new_tokens=8, episode_length=3, reward=1.5)
    obs_q, act_q = asyncio.Queue(), asyncio.Queue()

    async def run():
        async def feeder():
            for _ in range(3):
                await obs_q.get()
                await act_q.put(_bundle())

        task = asyncio.create_task(feeder())
        out = await agent.collect_trajectory(_prompt(), None, obs_q, act_q)
        await task
        return out

    samples = asyncio.run(run())
    assert len(samples) == 3  # one per episode turn
    # Per-turn ids: the sequence buffer keys by id, so turns must not collide.
    assert [x.ids[0] for x in samples] == ["q0-t0", "q0-t1", "q0-t2"]
    s = samples[0]
    assert s.data["rewards"].tolist() == [1.5, 1.5]
    assert s.data["packed_input_ids"].shape[0] == 5 + 6
    # prompt_mask covers exactly the prompt span of each group member
    assert s.data["prompt_mask"].sum() == 2 * 3
    assert s.data["seq_no_eos_mask"].tolist() == [1.0, 0.0]
    assert s.metadata["version_start"] == [3]


def test_null_agent_registered():
    a = make_agent(AgentAbstraction("null", args=dict(max_new_tokens=4)))
    assert isinstance(a, NullAgent)

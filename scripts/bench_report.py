#!/usr/bin/env python
"""Assemble a BENCH_rNN-style JSON from the benchmark bank.

    python scripts/bench_report.py --bank /tmp/areal_bench_bank \
        --out BENCH_r06.json [--multichip MULTICHIP_r06.json] [--round r06]

Merges every banked phase record (with its attestation block) plus the
CPU/virtual-mesh proxy evidence — pack density, prefetch overlap, the
8-device dryrun passthrough from the newest MULTICHIP json — explicitly
labeled non-driver-verified. Run scripts/validate_bench.py on the output
before publishing it.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from areal_tpu.bench import report  # noqa: E402


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--bank", default=None, help="bank directory "
                        "(default: $AREAL_BENCH_BANK)")
    parser.add_argument("--out", default=None,
                        help="write the report here (default: stdout only)")
    parser.add_argument("--multichip", default=None,
                        help="MULTICHIP json to fold in as proxy evidence "
                             "(default: newest MULTICHIP_r*.json in repo)")
    parser.add_argument("--round", dest="round_tag", default=None)
    parser.add_argument("--line", action="store_true",
                        help="print the one-line driver JSON instead of "
                             "the full report")
    args = parser.parse_args(argv)

    rep = report.build_report(
        bank_path=args.bank, multichip_path=args.multichip,
        round_tag=args.round_tag,
    )
    if args.out:
        report.write_report(rep, args.out)
        print(f"wrote {args.out} ({len(rep['phases'])} driver phase(s), "
              f"{len(rep['proxy'])} proxy record(s), "
              f"driver_verified={rep['driver_verified']})", file=sys.stderr)
    print(json.dumps(report.result_line(rep) if args.line else rep,
                     indent=None if args.line else 1))
    return 0


if __name__ == "__main__":
    sys.exit(main())

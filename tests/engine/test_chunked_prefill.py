"""Chunked prefill (paged.paged_chunk_prefill): long prompts prefill
through ONE fixed-shape program instead of a compile per length bucket
(the reference's serving backend chunk-prefills long prompts the same
way). Correctness bar: bit-identical greedy generations vs the batched
prefill path."""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from areal_tpu.engine.paged import paged_chunk_prefill
from areal_tpu.engine.serving import GenRequest, ServingEngine, _prefill_batch
from areal_tpu.models.config import TransformerConfig
from areal_tpu.models.transformer import init_params


def small_cfg():
    return TransformerConfig(
        n_layers=2,
        hidden_dim=64,
        n_q_heads=4,
        n_kv_heads=2,
        head_dim=16,
        intermediate_dim=128,
        vocab_size=256,
        max_position_embeddings=512,
        compute_dtype="float32",
    )


@pytest.fixture(scope="module")
def model():
    cfg = small_cfg()
    return cfg, init_params(cfg, jax.random.PRNGKey(0))


def _run_engine(cfg, params, prompts, prefill_chunk, max_new=12):
    eng = ServingEngine(
        cfg,
        params,
        max_batch_size=4,
        max_seq_len=256,
        decode_block_steps=4,
        prompt_bucket=16,
        eos_token_id=None,
        page_size=16,
        prefill_chunk=prefill_chunk,
    )
    eng.start()
    try:
        done = threading.Event()
        results = {}

        def cb(res):
            results[res.qid] = res
            if len(results) == len(prompts):
                done.set()

        for i, p in enumerate(prompts):
            eng.submit(
                GenRequest(
                    qid=f"q{i}",
                    input_ids=list(p),
                    max_new_tokens=max_new,
                    greedy=True,
                    done_cb=cb,
                )
            )
        assert done.wait(300)
        return {q: r.output_ids for q, r in results.items()}
    finally:
        eng.stop()


def test_chunk_prefill_logits_match_batched(model):
    """Direct check: chunked prefill leaves the same last-token logits
    (and pool KV usable for them) as the one-shot batched prefill."""
    cfg, params = model
    rng = np.random.RandomState(3)
    plen = 50  # 4 chunks of 16 with a ragged tail
    ids = rng.randint(0, cfg.vocab_size, size=plen)

    pad = 64
    row = np.zeros((1, pad), np.int32)
    row[0, :plen] = ids
    ref_last, _, _ = _prefill_batch(
        params, cfg, jnp.asarray(row), jnp.asarray([plen], np.int32),
        pad_len=pad,
    )

    page = 16
    n_pages_needed = (plen + page - 1) // page
    n_pool = n_pages_needed + 2  # page 0 is the reserved trash sink
    # Pool layout matches the engine: [L, Hkv, N, page, hd].
    kp = jnp.zeros(
        (cfg.n_layers, cfg.n_kv_heads, n_pool, page, cfg.head_dim),
        jnp.float32,
    )
    vp = jnp.zeros_like(kp)
    prow = np.zeros((8,), np.int32)  # unused entries -> trash page 0
    prow[:n_pages_needed] = 1 + np.arange(n_pages_needed)
    C = 16
    last = None
    for s0 in range(0, plen, C):
        seg = ids[s0 : s0 + C]
        toks = np.zeros((C,), np.int32)
        toks[: len(seg)] = seg
        last, kp, vp = paged_chunk_prefill(
            params, cfg, jnp.asarray(toks), kp, vp, jnp.asarray(prow),
            jnp.asarray(s0, jnp.int32), jnp.asarray(len(seg), jnp.int32),
        )
    np.testing.assert_allclose(
        np.asarray(last), np.asarray(ref_last[0]), rtol=2e-4, atol=2e-4
    )


def test_chunked_engine_matches_batched_engine(model):
    """E2E: greedy generations are identical with and without chunked
    prefill, across ragged prompt lengths (incl. one shorter than the
    chunk, exercising the mixed long/short admit path)."""
    cfg, params = model
    rng = np.random.RandomState(7)
    prompts = [
        rng.randint(0, cfg.vocab_size, size=n).tolist()
        for n in (50, 17, 33, 8)  # chunk=16: 3 long + 1 short
    ]
    base = _run_engine(cfg, params, prompts, prefill_chunk=None)
    chunked = _run_engine(cfg, params, prompts, prefill_chunk=16)
    assert base.keys() == chunked.keys()
    for q in base:
        assert base[q] == chunked[q], f"{q} diverged"


def test_chunk_prefill_multi_subchunk_matches_batched(model, monkeypatch):
    """Force the SMEM-cap scan path (n_sub > 1): CPU pools are far too
    small to trip the real 512 KB budget, so shrink it until a 16-row
    chunk splits into two 8-row sub-chunks and pin the same last-logits
    identity as the single-call path (the on-chip failure this guards:
    [C, P] page-index prefetch overflowing the 1 MB SMEM at 16k ctx)."""
    cfg, params = model
    monkeypatch.setenv("AREAL_CHUNK_SMEM_BUDGET", "256")  # rows_cap -> 8
    paged_chunk_prefill.clear_cache()  # env is read at trace time
    try:
        rng = np.random.RandomState(7)
        plen = 50
        ids = rng.randint(0, cfg.vocab_size, size=plen)

        pad = 64
        row = np.zeros((1, pad), np.int32)
        row[0, :plen] = ids
        ref_last, _, _ = _prefill_batch(
            params, cfg, jnp.asarray(row), jnp.asarray([plen], np.int32),
            pad_len=pad,
        )

        page = 16
        n_pages_needed = (plen + page - 1) // page
        n_pool = n_pages_needed + 2
        kp = jnp.zeros(
            (cfg.n_layers, cfg.n_kv_heads, n_pool, page, cfg.head_dim),
            jnp.float32,
        )
        vp = jnp.zeros_like(kp)
        prow = np.zeros((8,), np.int32)
        prow[:n_pages_needed] = 1 + np.arange(n_pages_needed)
        C = 16  # splits into 2 sub-chunks of 8 under the tiny budget
        last = None
        for s0 in range(0, plen, C):
            seg = ids[s0 : s0 + C]
            toks = np.zeros((C,), np.int32)
            toks[: len(seg)] = seg
            last, kp, vp = paged_chunk_prefill(
                params, cfg, jnp.asarray(toks), kp, vp, jnp.asarray(prow),
                jnp.asarray(s0, jnp.int32), jnp.asarray(len(seg), jnp.int32),
            )
        np.testing.assert_allclose(
            np.asarray(last), np.asarray(ref_last[0]), rtol=2e-4, atol=2e-4
        )
    finally:
        paged_chunk_prefill.clear_cache()  # don't leak the tiny-budget trace

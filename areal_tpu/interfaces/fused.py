"""Fused reward+reference forward interface.

Counterpart of realhf/impl/model/interface/fused_interface.py
(FusedThreadingForwardInterface:23-71): runs several member interfaces'
`inference` over the same model/data in a thread pool and merges outputs
via SequenceSample.update_ — lets one model allocation serve both the
reward verification and the reference logprob pass.
"""

from __future__ import annotations

import dataclasses
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List

from areal_tpu.api.data_api import MicroBatchSpec, SequenceSample
from areal_tpu.api.model_api import (
    Model,
    ModelInterface,
    make_interface,
    register_interface,
)


@dataclasses.dataclass
class FusedThreadingForwardInterface(ModelInterface):
    interfaces: Dict[str, "ModelInterface | dict"] = dataclasses.field(
        default_factory=dict
    )

    def __post_init__(self):
        resolved = {}
        for name, itf in self.interfaces.items():
            if isinstance(itf, ModelInterface):
                resolved[name] = itf
            elif isinstance(itf, dict):
                resolved[name] = make_interface(
                    itf.get("type_", name), **itf.get("args", {})
                )
            else:
                resolved[name] = make_interface(itf)
        self.interfaces = resolved

    def inference(
        self, model: Model, input_: SequenceSample, mb_spec: MicroBatchSpec
    ) -> SequenceSample:
        with ThreadPoolExecutor(max_workers=max(len(self.interfaces), 1)) as ex:
            futures = {
                name: ex.submit(itf.inference, model, input_, mb_spec)
                for name, itf in self.interfaces.items()
            }
            results = {name: f.result() for name, f in futures.items()}
        out = None
        for name in sorted(results):
            r = results[name]
            if r is None:
                continue
            if out is None:
                out = r
            else:
                out.update_(r)
        return out


register_interface("fused-threading", FusedThreadingForwardInterface)

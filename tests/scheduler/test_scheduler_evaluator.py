"""Scheduler client + AutomaticEvaluator + offline eval harnesses
(VERDICT r2 weak #6: these previously had zero tests).

The end-to-end tests build a REAL tiny HF checkpoint (qwen2 family) plus
a trained WordPiece tokenizer, let the evaluator discover it, submit the
eval job through the local scheduler, and assert a score JSON lands —
the full reference flow (realhf/scheduler/evaluator.py:160-348).
"""

import json
import os
import sys
import time

import numpy as np
import pytest

from areal_tpu.scheduler.client import (
    JobException,
    JobState,
    LocalSchedulerClient,
    make_scheduler,
)
from areal_tpu.scheduler.evaluator import AutomaticEvaluator

# Eval subprocesses must not grab the real TPU (or the axon platform this
# environment injects); they are tiny CPU jobs.
CPU_ENV = {"JAX_PLATFORMS": "cpu"}


# ----------------------------------------------------------------------
# Scheduler client
# ----------------------------------------------------------------------


def test_local_scheduler_lifecycle(tmp_path):
    sched = LocalSchedulerClient(log_dir=str(tmp_path / "logs"))
    try:
        ok = sched.submit("ok", [sys.executable, "-c", "print('hi')"])
        bad = sched.submit("bad", [sys.executable, "-c", "raise SystemExit(3)"])
        infos = {i.name: i for i in sched.wait(
            ["ok", "bad"], timeout=60, raise_on_failure=False
        )}
        assert infos["ok"].state == JobState.COMPLETED
        assert infos["bad"].state == JobState.FAILED
        assert infos["bad"].exit_code == 3
        # Logs captured.
        assert "hi" in open(tmp_path / "logs" / "ok.log").read()
        # wait(raise_on_failure=True) surfaces the failure.
        with pytest.raises(JobException):
            sched.wait(["bad"], timeout=10)
        assert sched.find("nope").state == JobState.NOT_FOUND
    finally:
        sched.stop_all()


def test_local_scheduler_stop(tmp_path):
    sched = LocalSchedulerClient()
    try:
        sched.submit("sleep", [sys.executable, "-c", "import time; time.sleep(60)"])
        assert sched.find("sleep").state == JobState.RUNNING
        sched.stop("sleep")
        deadline = time.monotonic() + 10
        while sched.find("sleep").state == JobState.RUNNING:
            assert time.monotonic() < deadline, "job did not stop"
            time.sleep(0.1)
        assert sched.find("sleep").state == JobState.FAILED  # SIGTERM exit
    finally:
        sched.stop_all()


def test_make_scheduler_registry():
    assert isinstance(make_scheduler("local"), LocalSchedulerClient)
    with pytest.raises(ValueError, match="unknown scheduler"):
        make_scheduler("definitely-not-registered")


# ----------------------------------------------------------------------
# Checkpoint + data fixtures for the end-to-end evaluator flow
# ----------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_ckpt(tmp_path_factory):
    """save_root/step2/dp0 with a real qwen2-format checkpoint + tokenizer."""
    import jax

    from areal_tpu.models.config import TransformerConfig
    from areal_tpu.models.hf import save_hf_model
    from areal_tpu.models.transformer import init_params
    from tests.fixtures import random_sentence, train_tiny_tokenizer

    root = tmp_path_factory.mktemp("save_root")
    ckpt = root / "step2" / "dp0"
    ckpt.mkdir(parents=True)

    import random as _random

    rng = _random.Random(0)
    texts = [random_sentence(rng) for _ in range(50)] + ["12 boxed"]
    tokenizer = train_tiny_tokenizer(texts, ckpt)

    cfg = TransformerConfig(
        n_layers=2, hidden_dim=32, n_q_heads=2, n_kv_heads=1, head_dim=16,
        intermediate_dim=64, vocab_size=tokenizer.vocab_size + 8,
        max_position_embeddings=256, attn_bias=True,  # qwen2 has qkv bias
        compute_dtype="float32", param_dtype="float32",
    )
    params = init_params(cfg, jax.random.PRNGKey(0))
    save_hf_model(str(ckpt), cfg, params, "qwen2")
    tokenizer.save_pretrained(str(ckpt))
    return str(root), str(ckpt)


@pytest.fixture(scope="module")
def math_data(tmp_path_factory):
    d = tmp_path_factory.mktemp("data") / "math.jsonl"
    rows = [
        {"query_id": "m0", "prompt": "one two three", "solutions": ["\\boxed{12}"]},
        {"query_id": "m1", "prompt": "alpha beta", "solutions": ["\\boxed{7}"]},
    ]
    with open(d, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    return str(d)


@pytest.fixture(scope="module")
def code_data(tmp_path_factory):
    d = tmp_path_factory.mktemp("data") / "code.jsonl"
    rows = [
        {
            "query_id": "c0",
            "prompt": "sum two ints",
            "input_output": {"inputs": ["1 2\n"], "outputs": ["3\n"]},
        },
    ]
    with open(d, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    return str(d)


# ----------------------------------------------------------------------
# AutomaticEvaluator end-to-end
# ----------------------------------------------------------------------


@pytest.mark.slow  # ~28s: full eval pipeline; preset/unit tests stay tier-1
def test_evaluator_math_end_to_end(tiny_ckpt, math_data, tmp_path):
    save_root, _ = tiny_ckpt
    ev = AutomaticEvaluator(
        save_root=save_root,
        data_path=math_data,
        output_root=str(tmp_path / "out"),
        eval_args={"max_new_tokens": 8, "greedy": True},
        job_env=CPU_ENV,
    )
    try:
        ev.run_until_idle(timeout=600)
    finally:
        ev.scheduler.stop_all()
    results = ev.results()
    assert 2 in results
    assert 0.0 <= results[2] <= 1.0
    out = json.load(open(tmp_path / "out" / "step2.json"))
    assert out["n_prompts"] == 2 and len(out["details"]) == 2


@pytest.mark.slow  # ~27s: full eval pipeline; preset/unit tests stay tier-1
def test_evaluator_code_end_to_end(tiny_ckpt, code_data, tmp_path):
    """A code checkpoint eval produces a score JSON (VERDICT r2 item 10)."""
    save_root, _ = tiny_ckpt
    ev = AutomaticEvaluator(
        save_root=save_root,
        data_path=code_data,
        output_root=str(tmp_path / "out"),
        eval_args={"max_new_tokens": 8, "greedy": True, "case_timeout": 10.0},
        task="code",
        job_env=CPU_ENV,
    )
    try:
        ev.run_until_idle(timeout=600)
    finally:
        ev.scheduler.stop_all()
    out = json.load(open(tmp_path / "out" / "step2.json"))
    assert out["task"] == "code"
    assert out["n_prompts"] == 1
    # A random model doesn't emit valid code; accuracy must be graded 0.
    assert out["accuracy"] == 0.0


def test_evaluator_rejects_unknown_task(tmp_path):
    with pytest.raises(ValueError, match="unknown eval task"):
        AutomaticEvaluator(
            save_root=str(tmp_path), data_path="x", output_root=str(tmp_path),
            task="vision",
        )


# ----------------------------------------------------------------------
# eval_and_aggregate over both families
# ----------------------------------------------------------------------


def test_eval_and_aggregate(tiny_ckpt, math_data, code_data, tmp_path):
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    from evaluation.eval_and_aggregate import Benchmark, eval_and_aggregate

    save_root, _ = tiny_ckpt
    benches = Benchmark.parse_many(
        f"math:{math_data}:math,code:{code_data}:code"
    )
    agg = eval_and_aggregate(
        save_root, benches, str(tmp_path / "agg"),
        max_new_tokens=8, greedy=True,
    )
    assert "step2" in agg["table"]
    row = agg["table"]["step2"]
    assert set(row) == {"math", "code", "avg"}
    assert os.path.exists(tmp_path / "agg" / "aggregate.json")
    # Idempotent rerun reuses results.json files.
    agg2 = eval_and_aggregate(
        save_root, benches, str(tmp_path / "agg"),
        max_new_tokens=8, greedy=True,
    )
    assert agg2["table"] == agg["table"]


def test_math_eval_multisample_metrics(tiny_ckpt, math_data):
    """n_samples > 1 reports pass@k and majority-vote accuracy
    (reference evaluation/rm_maj_eval.py)."""
    from evaluation.math_eval import evaluate_checkpoint

    _, ckpt = tiny_ckpt
    res = evaluate_checkpoint(
        ckpt=ckpt, data=math_data, n_samples=2, greedy=False,
        temperature=1.0, max_new_tokens=8,
    )
    assert 0.0 <= res["maj_at_k"] <= res["pass_at_k"] <= 1.0
    assert res["n_samples"] == 2
    assert len(res["details"]) == 2 * res["n_prompts"]


def test_math_eval_named_benchmark_preset(tiny_ckpt, tmp_path):
    """benchmark= drives the full preset path e2e: field mapping (problem/
    answer rows), prompt template + few-shot demos, multi-sample metrics
    (VERDICT r4 missing #2 / next-round #5)."""
    from evaluation.math_eval import evaluate_checkpoint

    _, ckpt = tiny_ckpt
    rows = [
        {"problem": "What is 20 + 22?", "answer": "42", "query_id": "p0"},
        {"problem": "What is 5 * 5?", "answer": "25", "query_id": "p1"},
    ]
    data = tmp_path / "math500.jsonl"
    data.write_text("".join(json.dumps(r) + "\n" for r in rows))

    res = evaluate_checkpoint(
        ckpt=ckpt, data=str(data), benchmark="math500",
        # Tiny-model overrides: the preset's 4096 new tokens would crawl.
        max_new_tokens=8, n_samples=2,
        output=str(tmp_path / "res.json"),
    )
    assert res["benchmark"] == "math500"
    assert res["prompt_type"] == "boxed"
    assert res["n_prompts"] == 2
    assert res["n_samples"] == 2
    assert len(res["details"]) == 4
    assert "pass_at_k" in res and "maj_at_k" in res
    saved = json.loads((tmp_path / "res.json").read_text())
    assert saved["benchmark"] == "math500"


def test_eval_and_aggregate_applies_preset(tiny_ckpt, tmp_path):
    """A benchmark whose NAME matches a preset routes through it (prompt
    template + defaults) inside the aggregation driver."""
    from evaluation.eval_and_aggregate import Benchmark, run_eval

    _, ckpt = tiny_ckpt
    rows = [{"problem": "What is 1 + 1?", "answer": "2"}]
    data = tmp_path / "amc.jsonl"
    data.write_text("".join(json.dumps(r) + "\n" for r in rows))

    res = run_eval(
        ckpt, Benchmark("amc23", str(data), "math"),
        str(tmp_path / "out.json"),
        max_new_tokens=8, n_samples=1, greedy=True,
    )
    assert res["benchmark"] == "amc23"
    assert res["prompt_type"] == "boxed"


def test_math_eval_python_answer_mode(tiny_ckpt, tmp_path):
    """answer_mode='python' drives the PAL grading path e2e (the tiny
    model emits no code block, so accuracy is 0 — the pipeline must
    handle that gracefully, not crash)."""
    from evaluation.math_eval import evaluate_checkpoint

    _, ckpt = tiny_ckpt
    rows = [{"problem": "What is 2 + 2?", "answer": "4"}]
    data = tmp_path / "pal.jsonl"
    data.write_text(json.dumps(rows[0]) + "\n")
    res = evaluate_checkpoint(
        ckpt=ckpt, data=str(data), benchmark="math500",
        prompt_type="pal", num_shots=1, answer_mode="python",
        max_new_tokens=8, n_samples=1,
    )
    assert res["answer_mode"] == "python"
    assert res["prompt_type"] == "pal"
    assert res["accuracy"] == 0.0

    with pytest.raises(ValueError, match="answer_mode"):
        evaluate_checkpoint(
            ckpt=ckpt, data=str(data), benchmark="math500",
            answer_mode="exec",
        )

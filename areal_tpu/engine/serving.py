"""Continuous-batching generation engine for the JAX generation server.

TPU-native replacement for the reference's patched-SGLang server stack
(realhf/impl/model/backend/sglang.py + patch/sglang/v0.4.6.post2.patch):
a fixed pool of B sequence slots over a static [L, B, S] KV cache, a
jitted multi-step decode block, per-slot sampling params, and
interruption BETWEEN blocks — which is what makes weight updates cheap:
the loop drains at a block boundary, partial outputs return to the
clients (who resubmit with the concatenated prefix, recomputing KV under
the new weights), and the new params are swapped in.

Static shapes throughout: prompt lengths are bucketed for prefill, the
decode block is one compiled program reused for the server's lifetime.
"""

from __future__ import annotations

import dataclasses
import functools
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from areal_tpu.base import logging
from areal_tpu.models.config import TransformerConfig
from areal_tpu.models.generation import decode_step, prefill
from areal_tpu.ops.sampling import NEG_INF, apply_top_k, apply_top_p

logger = logging.getLogger("serving")


@dataclasses.dataclass
class GenRequest:
    qid: str
    input_ids: List[int]
    max_new_tokens: int = 256
    min_new_tokens: int = 0
    greedy: bool = False
    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = -1
    stop_token_ids: Tuple[int, ...] = ()
    # resolved by the engine loop:
    done_cb: Optional[Callable[["GenResult"], None]] = None
    submit_time: float = 0.0


@dataclasses.dataclass
class GenResult:
    qid: str
    output_ids: List[int]
    output_logprobs: List[float]
    no_eos: bool  # True if stopped for a non-EOS reason (budget/interrupt)
    interrupted: bool
    version_start: int
    version_end: int
    latency: float = 0.0


def _pad_bucket(n: int, multiple: int) -> int:
    return max(multiple, -(-n // multiple) * multiple)


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "n_steps"),
    donate_argnames=("k_cache", "v_cache"),
)
def _decode_block(
    params,
    cfg: TransformerConfig,
    k_cache,
    v_cache,
    lengths,  # [B] cache fill per slot (incl. already-emitted tokens)
    next_input,  # [B] last sampled token, to feed
    active,  # [B] bool
    remaining,  # [B] int32 budget left
    min_remaining,  # [B] int32 forbid-EOS countdown
    temps,  # [B]
    top_ps,  # [B]
    top_ks,  # [B] int32 (<=0 disables)
    greedy_mask,  # [B] bool
    eos_mask,  # [V] bool — True at stop-token columns
    rng,
    n_steps: int,
):
    """Run up to n_steps decode steps for every active slot.

    Returns (out_tokens [B, n], out_logprobs [B, n], emitted_mask [B, n],
    state...) — slots that finish (EOS or budget) flip inactive mid-block;
    `no_eos` is derivable on host from which stop fired.
    """
    B = lengths.shape[0]

    def body(i, carry):
        (kc, vc, lengths, next_input, active, remaining, min_remaining,
         rng, out_t, out_lp, out_m, hit_eos) = carry
        logits, kc, vc = decode_step(params, cfg, next_input, kc, vc, lengths)
        rng, sub = jax.random.split(rng)
        logits = logits.astype(jnp.float32)
        V = logits.shape[-1]
        # forbid stop tokens while min_new_tokens not reached
        forbid = (min_remaining > 0)[:, None] & eos_mask[None, :]
        logits = jnp.where(forbid, NEG_INF, logits)
        base_logp = jax.nn.log_softmax(logits, axis=-1)
        warped = logits / jnp.maximum(temps[:, None], 1e-6)
        # ONE descending sort serves both warps: the per-row top-k threshold
        # and the top-p nucleus cutoff (two independent sorts would double
        # the dominant per-step sampling cost at real vocab sizes).
        sorted_desc = jnp.sort(warped, axis=-1)[:, ::-1]
        k_eff = jnp.where(top_ks <= 0, V, jnp.minimum(top_ks, V))
        kth = jnp.take_along_axis(sorted_desc, (k_eff - 1)[:, None], axis=-1)
        probs = jax.nn.softmax(sorted_desc, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep_sorted = (cum - probs) < top_ps[:, None]
        cutoff_idx = jnp.sum(keep_sorted, axis=-1, keepdims=True) - 1
        p_cut = jnp.take_along_axis(sorted_desc, cutoff_idx, axis=-1)
        warped = jnp.where(warped < jnp.maximum(kth, p_cut), NEG_INF, warped)
        sampled = jax.random.categorical(sub, warped, axis=-1)
        argmax = jnp.argmax(logits, axis=-1)
        tokens = jnp.where(greedy_mask, argmax, sampled).astype(jnp.int32)
        logprobs = jnp.take_along_axis(base_logp, tokens[:, None], axis=-1)[:, 0]

        emit = active
        tokens = jnp.where(emit, tokens, 0)
        logprobs = jnp.where(emit, logprobs, 0.0)
        out_t = out_t.at[:, i].set(tokens)
        out_lp = out_lp.at[:, i].set(logprobs)
        out_m = out_m.at[:, i].set(emit)

        is_eos = eos_mask[tokens] & emit
        remaining = remaining - emit.astype(jnp.int32)
        min_remaining = jnp.maximum(min_remaining - emit.astype(jnp.int32), 0)
        exhausted = (remaining <= 0) & emit
        hit_eos = hit_eos | is_eos
        active = active & ~is_eos & ~exhausted
        lengths = lengths + emit.astype(lengths.dtype)
        next_input = tokens
        return (kc, vc, lengths, next_input, active, remaining, min_remaining,
                rng, out_t, out_lp, out_m, hit_eos)

    out_t = jnp.zeros((B, n_steps), jnp.int32)
    out_lp = jnp.zeros((B, n_steps), jnp.float32)
    out_m = jnp.zeros((B, n_steps), bool)
    hit_eos = jnp.zeros((B,), bool)
    carry = (k_cache, v_cache, lengths, next_input, active, remaining,
             min_remaining, rng, out_t, out_lp, out_m, hit_eos)
    carry = jax.lax.fori_loop(0, n_steps, body, carry)
    (k_cache, v_cache, lengths, next_input, active, remaining, min_remaining,
     rng, out_t, out_lp, out_m, hit_eos) = carry
    return (out_t, out_lp, out_m, hit_eos, k_cache, v_cache, lengths,
            next_input, active, remaining, min_remaining, rng)


@functools.partial(jax.jit, static_argnames=("cfg", "pad_len"))
def _prefill_one(params, cfg: TransformerConfig, input_ids, length, pad_len: int):
    """Prefill a single sequence (batch of 1) at a bucketed length.

    Returns (last_logits [V], (k_pref, v_pref) each [L, pad_len, Hkv, hd])."""
    from areal_tpu.models.transformer import forward as packed_forward

    ids = input_ids[None, :]  # [1, P]
    pos = jnp.arange(pad_len)[None, :]
    seg = (pos < length).astype(jnp.int32)
    positions = jnp.where(seg > 0, pos, 0).astype(jnp.int32)
    logits, (k, v) = packed_forward(params, cfg, ids, seg, positions, return_kv=True)
    last = jnp.take_along_axis(
        logits, jnp.maximum(length - 1, 0)[None, None, None], axis=1
    )[0, 0]
    return last, (k[:, 0], v[:, 0])


class ServingEngine:
    """Slot-pool continuous-batching engine driven by a background thread."""

    def __init__(
        self,
        cfg: TransformerConfig,
        params,
        max_batch_size: int = 8,
        max_seq_len: int = 2048,
        decode_block_steps: int = 16,
        prompt_bucket: int = 64,
        eos_token_id: Optional[int] = None,
        seed: int = 1,
    ):
        self.cfg = cfg
        self.params = params
        self.B = max_batch_size
        self.S = max_seq_len
        self.block_steps = decode_block_steps
        self.prompt_bucket = prompt_bucket
        self.eos_token_id = eos_token_id
        self.version = 0

        self._k_cache = None
        self._v_cache = None
        self._lengths = jnp.zeros((self.B,), jnp.int32)
        self._next_input = jnp.zeros((self.B,), jnp.int32)
        self._active = jnp.zeros((self.B,), bool)
        self._remaining = jnp.zeros((self.B,), jnp.int32)
        self._min_remaining = jnp.zeros((self.B,), jnp.int32)
        self._temps = jnp.ones((self.B,), jnp.float32)
        self._top_ps = jnp.ones((self.B,), jnp.float32)
        self._top_ks = jnp.full((self.B,), -1, jnp.int32)
        self._greedy = jnp.zeros((self.B,), bool)
        self._rng = jax.random.PRNGKey(seed)

        # host-side slot bookkeeping
        self._slot_req: List[Optional[GenRequest]] = [None] * self.B
        self._slot_out: List[List[int]] = [[] for _ in range(self.B)]
        self._slot_lp: List[List[float]] = [[] for _ in range(self.B)]
        self._slot_vstart: List[int] = [0] * self.B

        self._queue: "queue.Queue[GenRequest]" = queue.Queue()
        self._lock = threading.Lock()
        self._interrupt = threading.Event()
        self._pending_params = None
        self._pending_version: Optional[int] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # metrics
        self.n_running = 0
        self.n_used_tokens = 0
        self.total_generated = 0

    # ------------------------------------------------------------------
    # Public API
    # ------------------------------------------------------------------

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=10)

    def submit(self, req: GenRequest):
        req.submit_time = time.monotonic()
        self._queue.put(req)

    def update_params(self, params, allow_interrupt: bool = True,
                      version: Optional[int] = None):
        """Swap weights at the next block boundary. With allow_interrupt,
        running requests are interrupted and returned partially (the AReaL
        protocol); without it, admission pauses and the swap happens once
        running requests drain. `version` pins the new weight version to
        the trainer's published one (self-incrementing would drift when
        the trainer publishes faster than the manager flushes)."""
        with self._lock:
            self._pending_params = params
            self._pending_version = version
        if allow_interrupt:
            self._interrupt.set()

    def metrics(self) -> Dict[str, float]:
        return {
            "num_running_reqs": float(self.n_running),
            "num_used_tokens": float(self.n_used_tokens),
            "total_generated": float(self.total_generated),
            "queue_depth": float(self._queue.qsize()),
        }

    # ------------------------------------------------------------------
    # Engine loop
    # ------------------------------------------------------------------

    def _ensure_cache(self):
        if self._k_cache is not None:
            return
        # shape probe via a 1-token prefill
        c = self.cfg
        n_layers = c.n_layers
        cdt = jnp.dtype(c.compute_dtype)
        self._k_cache = jnp.zeros(
            (n_layers, self.B, self.S, c.n_kv_heads, c.head_dim), cdt
        )
        self._v_cache = jnp.zeros_like(self._k_cache)

    def _free_slots(self) -> List[int]:
        return [i for i in range(self.B) if self._slot_req[i] is None]

    def _admit(self):
        """Fill free slots from the queue (prefill each)."""
        # Drain semantics for non-interrupting weight updates: stop
        # admitting so running requests finish and the swap can land.
        if self._pending_params is not None:
            return
        free = self._free_slots()
        while free and not self._queue.empty():
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            slot = free.pop(0)
            plen = len(req.input_ids)
            if plen + req.max_new_tokens > self.S:
                # Trim budget to fit the static cache.
                req.max_new_tokens = max(0, self.S - plen)
            if plen >= self.S or req.max_new_tokens == 0:
                self._finish_host(req, [], [], no_eos=True, interrupted=False,
                                  vstart=self.version)
                continue
            pad = _pad_bucket(plen, self.prompt_bucket)
            pad = min(pad, self.S)
            ids = np.zeros((pad,), np.int32)
            ids[:plen] = req.input_ids
            last_logits, (k_pref, v_pref) = _prefill_one(
                self.params, self.cfg, jnp.asarray(ids),
                jnp.asarray(plen, jnp.int32), pad_len=pad,
            )
            # Sample the first token on host-side jit (scalar batch).
            self._rng, sub = jax.random.split(self._rng)
            tok, lp = _sample_first(
                last_logits, sub, req.greedy, req.temperature, req.top_p,
                req.top_k, jnp.asarray(self._eos_mask_np(req)),
                req.min_new_tokens > 0,
            )
            tok_i, lp_f = int(tok), float(lp)
            self._k_cache = self._k_cache.at[:, slot, :pad].set(k_pref)
            self._v_cache = self._v_cache.at[:, slot, :pad].set(v_pref)
            # host bookkeeping
            self._slot_req[slot] = req
            self._slot_out[slot] = [tok_i]
            self._slot_lp[slot] = [lp_f]
            self._slot_vstart[slot] = self.version
            is_eos = tok_i in self._eos_set(req)
            budget_left = req.max_new_tokens - 1
            if (is_eos and req.min_new_tokens <= 1) or budget_left <= 0:
                self._finish_slot(slot, hit_eos=is_eos)
                continue
            # device state. `lengths` counts cache fill EXCLUDING the pending
            # next_input token: the first decode step writes the sampled
            # first token's k/v at position plen, then advances.
            self._lengths = self._lengths.at[slot].set(plen)
            self._next_input = self._next_input.at[slot].set(tok_i)
            self._active = self._active.at[slot].set(True)
            self._remaining = self._remaining.at[slot].set(budget_left)
            self._min_remaining = self._min_remaining.at[slot].set(
                max(0, req.min_new_tokens - 1)
            )
            self._temps = self._temps.at[slot].set(req.temperature)
            self._top_ps = self._top_ps.at[slot].set(req.top_p)
            self._top_ks = self._top_ks.at[slot].set(req.top_k)
            self._greedy = self._greedy.at[slot].set(req.greedy)

    def _eos_set(self, req: Optional[GenRequest]) -> set:
        s = set(req.stop_token_ids) if req is not None else set()
        if self.eos_token_id is not None:
            s.add(self.eos_token_id)
        return s

    def _eos_mask_np(self, req: Optional[GenRequest] = None) -> np.ndarray:
        """[V] bool mask of stop-token columns (empty set -> all False;
        an index-based encoding would need a pad index, and any pad value
        lands on a real vocab column)."""
        mask = np.zeros((self.cfg.vocab_size,), bool)
        for t in self._eos_set(req):
            if 0 <= t < self.cfg.vocab_size:
                mask[t] = True
        return mask

    def _finish_host(self, req, out, lps, no_eos, interrupted, vstart):
        res = GenResult(
            qid=req.qid,
            output_ids=list(out),
            output_logprobs=list(lps),
            no_eos=no_eos,
            interrupted=interrupted,
            version_start=vstart,
            version_end=self.version,
            latency=time.monotonic() - req.submit_time,
        )
        self.total_generated += len(out)
        if req.done_cb:
            req.done_cb(res)

    def _finish_slot(self, slot: int, hit_eos: bool, interrupted: bool = False):
        req = self._slot_req[slot]
        self._finish_host(
            req, self._slot_out[slot], self._slot_lp[slot],
            no_eos=not hit_eos, interrupted=interrupted,
            vstart=self._slot_vstart[slot],
        )
        self._slot_req[slot] = None
        self._slot_out[slot] = []
        self._slot_lp[slot] = []
        self._active = self._active.at[slot].set(False)
        self._lengths = self._lengths.at[slot].set(0)

    def _interrupt_all(self):
        for slot in range(self.B):
            if self._slot_req[slot] is not None:
                self._finish_slot(slot, hit_eos=False, interrupted=True)

    def _apply_pending_params(self):
        with self._lock:
            pending = self._pending_params
            version = self._pending_version
            self._pending_params = None
            self._pending_version = None
        if pending is not None:
            self.params = jax.tree_util.tree_map(jnp.asarray, pending)
            self.version = version if version is not None else self.version + 1
            logger.info(f"serving engine weights updated to v{self.version}")
        self._interrupt.clear()

    def _loop(self):
        self._ensure_cache()
        eos_global = jnp.asarray(self._eos_mask_np())
        while not self._stop.is_set():
            if self._interrupt.is_set():
                self._interrupt_all()
                self._apply_pending_params()
            self._admit()
            if not any(r is not None for r in self._slot_req):
                # idle: apply updates immediately, then wait for work
                if self._pending_params is not None:
                    self._apply_pending_params()
                time.sleep(0.002)
                self.n_running = 0
                continue
            self.n_running = sum(r is not None for r in self._slot_req)
            self.n_used_tokens = int(jnp.sum(self._lengths))

            self._rng, sub = jax.random.split(self._rng)
            (out_t, out_lp, out_m, hit_eos, self._k_cache, self._v_cache,
             self._lengths, self._next_input, self._active, self._remaining,
             self._min_remaining, _) = _decode_block(
                self.params, self.cfg, self._k_cache, self._v_cache,
                self._lengths, self._next_input, self._active,
                self._remaining, self._min_remaining, self._temps,
                self._top_ps, self._top_ks, self._greedy, eos_global, sub,
                n_steps=self.block_steps,
            )
            out_t = np.asarray(out_t)
            out_lp_h = np.asarray(out_lp)
            out_m_h = np.asarray(out_m)
            hit_eos_h = np.asarray(hit_eos)
            active_h = np.asarray(self._active)
            for slot in range(self.B):
                req = self._slot_req[slot]
                if req is None:
                    continue
                emitted = out_m_h[slot]
                n = int(emitted.sum())
                if n:
                    self._slot_out[slot].extend(out_t[slot, :][emitted].tolist())
                    self._slot_lp[slot].extend(out_lp_h[slot, :][emitted].tolist())
                # Per-request extra stop tokens (beyond the global EOS set)
                # are enforced on host: trim at the first occurrence AFTER
                # the min_new_tokens floor (the device forbid mask only
                # covers the global EOS set).
                extra = set(req.stop_token_ids) - self._eos_set(None)
                if extra:
                    for j, t in enumerate(self._slot_out[slot]):
                        if j < req.min_new_tokens:
                            continue
                        if t in extra:
                            self._slot_out[slot] = self._slot_out[slot][: j + 1]
                            self._slot_lp[slot] = self._slot_lp[slot][: j + 1]
                            self._finish_slot(slot, hit_eos=True)
                            break
                    if self._slot_req[slot] is None:
                        continue
                if not active_h[slot]:
                    self._finish_slot(slot, hit_eos=bool(hit_eos_h[slot]))
        # drain on stop
        self._interrupt_all()


@functools.partial(jax.jit, static_argnames=("greedy", "top_k", "forbid"))
def _sample_first(logits, rng, greedy: bool, temperature, top_p, top_k: int,
                  eos_mask, forbid: bool):
    logits = logits.astype(jnp.float32)[None, :]
    if forbid:
        logits = jnp.where(eos_mask[None, :], NEG_INF, logits)
    base_logp = jax.nn.log_softmax(logits, axis=-1)
    if greedy:
        tok = jnp.argmax(logits, axis=-1)
    else:
        warped = logits / jnp.maximum(jnp.asarray(temperature, jnp.float32), 1e-6)
        warped = apply_top_k(warped, top_k)
        warped = apply_top_p(warped, jnp.asarray(top_p, jnp.float32))
        tok = jax.random.categorical(rng, warped, axis=-1)
    lp = jnp.take_along_axis(base_logp, tok[:, None], axis=-1)[0, 0]
    return tok[0].astype(jnp.int32), lp

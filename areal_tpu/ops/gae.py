"""Generalized Advantage Estimation over packed rows.

Replaces the reference's cugae CUDA kernels (csrc/cugae/gae.cu:
gae_1d_nolp_misalign et al.) and their Python fallback
(realhf/impl/model/utils/ppo_functional.py:292-324) with three
TPU-native implementations over one shared formulation:

- ``gae_rows`` — reverse ``lax.scan`` over the time axis, vectorized
  across rows: O(T) *sequential* steps. The original implementation
  and the numerical oracle every other impl is pinned against.
- ``gae_rows_assoc`` — segment-aware ``jax.lax.associative_scan``:
  the GAE recursion A_t = delta_t + (gamma*lam)*[same-seg]*A_{t+1} is a
  first-order linear recurrence, i.e. a reverse scan of affine maps
  f_t(x) = a_t*x + b_t under composition — associative, so XLA runs it
  in O(log T) depth instead of T serial dispatches. Measured 2x faster
  than the serial scan on CPU at [8, 4096] (kernel_micro_gae banks the
  ongoing evidence); on TPU the win is the whole point: the serial scan
  is T tiny dependent ops.
- ``gae_rows_pallas`` — the same affine scan as a blocked Pallas kernel
  (ops/pallas/gae_scan.py): ONE HBM read of (a, b) + one write of the
  result vs associative_scan's log T full-array passes. Shape-gated
  (``gae_pallas_ok``); interpret-mode on non-TPU backends, so it is
  parity-testable everywhere but only *fast* on device.

``packed_gae`` dispatches (``impl='auto'|'scan'|'assoc'|'pallas'``,
mirroring ops/attention.resolve_attn_impl): 'auto' resolves to the
associative scan everywhere — Pallas stays opt-in until a device
window banks kernel_micro_gae evidence for the crossover
(docs/perf_notes.md "Round 15").

Inputs are [R, T] row-packed (multiple sequences per row, segment ids,
0 = padding). Bootstrapping for truncated (no-EOS) sequences is expressed
by placing V(s_T) in `bootstrap` at each sequence's final token.

Parity: the three impls reassociate float32 sums differently, so they
agree to ~1e-6 relative on realistic magnitudes (pinned in
tests/ops/test_gae.py); at lam = 0 nothing accumulates and they agree
to one ulp (XLA FMA fusion still moves the last bit).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def gae_rows(
    rewards: jnp.ndarray,  # [R, T] per-token rewards
    values: jnp.ndarray,  # [R, T] V(s_t)
    segment_ids: jnp.ndarray,  # [R, T]
    bootstrap: jnp.ndarray,  # [R, T] V(s_{T+1}) at final tokens of truncated seqs, else 0
    gamma: float = 1.0,
    lam: float = 1.0,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (advantages, returns), both [R, T], zero outside segments.

    delta_t = r_t + gamma * V(s_{t+1}) - V(s_t), with V(s_{t+1}) = the next
    token's value within the same segment, the bootstrap value at segment
    ends, 0 otherwise. A_t = delta_t + gamma*lam*A_{t+1} (same-segment).
    """
    R, T = rewards.shape
    rewards = rewards.astype(jnp.float32)
    values = values.astype(jnp.float32)
    bootstrap = bootstrap.astype(jnp.float32)

    def step(carry, xs):
        adv_next, v_next, seg_next = carry
        r_t, v_t, seg_t, boot_t = xs  # each [R]
        valid = seg_t > 0
        same = (seg_t == seg_next) & valid
        v_tp1 = jnp.where(same, v_next, boot_t)
        delta = r_t + gamma * v_tp1 - v_t
        adv = delta + gamma * lam * jnp.where(same, adv_next, 0.0)
        adv = jnp.where(valid, adv, 0.0)
        return (adv, v_t, seg_t), adv

    xs = (rewards.T, values.T, segment_ids.T, bootstrap.T)  # scan over T
    init = (
        jnp.zeros((R,), jnp.float32),
        jnp.zeros((R,), jnp.float32),
        jnp.zeros((R,), jnp.int32),
    )
    _, advs = jax.lax.scan(step, init, xs, reverse=True)
    advantages = advs.T
    returns = advantages + values
    valid = segment_ids > 0
    return (
        jnp.where(valid, advantages, 0.0),
        jnp.where(valid, returns, 0.0),
    )


def _gae_affine_elems(rewards, values, segment_ids, bootstrap, gamma, lam):
    """(a, b, valid, values32): the per-token affine scan elements.

    The GAE recursion is x_t = a_t * x_{t+1} + b_t with
    a_t = gamma*lam*[seg_t == seg_{t+1}, both valid] and b_t = delta_t.
    Computed in one vectorized pass (no neighbor access inside the scan):
    V(s_{t+1}) is the left-shifted values where the NEXT token shares the
    segment, the bootstrap at segment ends — exactly the serial scan's
    carry semantics, including its t = T-1 edge (carry seg 0 => same is
    False there, matching the shifted pad of 0 segment ids)."""
    rewards = rewards.astype(jnp.float32)
    values = values.astype(jnp.float32)
    bootstrap = bootstrap.astype(jnp.float32)
    valid = segment_ids > 0
    seg_next = jnp.concatenate(
        [segment_ids[:, 1:], jnp.zeros_like(segment_ids[:, :1])], axis=1
    )
    v_next = jnp.concatenate(
        [values[:, 1:], jnp.zeros_like(values[:, :1])], axis=1
    )
    same = (segment_ids == seg_next) & valid
    v_tp1 = jnp.where(same, v_next, bootstrap)
    delta = rewards + gamma * v_tp1 - values
    a = jnp.where(same, jnp.float32(gamma * lam), 0.0)
    # Masking b here makes invalid positions exact zeros (a is already 0
    # there, so they also never leak into neighbors) — the serial scan's
    # post-hoc where(valid, ., 0) built into the elements.
    b = jnp.where(valid, delta, 0.0)
    return a, b, valid, values


def _finish_gae(adv, values32, valid):
    adv = jnp.where(valid, adv, 0.0)
    return adv, jnp.where(valid, adv + values32, 0.0)


def gae_rows_assoc(
    rewards: jnp.ndarray,
    values: jnp.ndarray,
    segment_ids: jnp.ndarray,
    bootstrap: jnp.ndarray,
    gamma: float = 1.0,
    lam: float = 1.0,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """``gae_rows`` semantics in O(log T) scan depth
    (``jax.lax.associative_scan`` over the affine elements)."""
    a, b, valid, values32 = _gae_affine_elems(
        rewards, values, segment_ids, bootstrap, gamma, lam
    )

    def combine(l, r):
        # reverse=True flips the array before a forward tree scan, so the
        # LEFT operand holds the LATER timesteps — the inner composition,
        # applied first: (f_outer . f_inner)(x) = a_o*(a_i*x + b_i) + b_o.
        a_inner, b_inner = l
        a_outer, b_outer = r
        return a_outer * a_inner, b_outer + a_outer * b_inner

    _, adv = jax.lax.associative_scan(combine, (a, b), reverse=True, axis=1)
    return _finish_gae(adv, values32, valid)


def gae_rows_pallas(
    rewards: jnp.ndarray,
    values: jnp.ndarray,
    segment_ids: jnp.ndarray,
    bootstrap: jnp.ndarray,
    gamma: float = 1.0,
    lam: float = 1.0,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """``gae_rows`` semantics as a blocked Pallas scan kernel.

    Shapes must pass ``gae_pallas_ok`` (T lane-aligned, R sublane-
    aligned); callers going through ``packed_gae(impl='auto')`` never
    reach this without the gate. Runs interpreted off-TPU."""
    from areal_tpu.ops.pallas.gae_scan import (
        gae_pallas_ok,
        segment_scan_reverse,
    )

    R, T = rewards.shape
    if not gae_pallas_ok(R, T):
        raise ValueError(
            f"gae impl='pallas' needs lane/sublane-aligned rows "
            f"(T % 128 == 0, R % 8 == 0), got [R={R}, T={T}]; use "
            f"impl='assoc'"
        )
    a, b, valid, values32 = _gae_affine_elems(
        rewards, values, segment_ids, bootstrap, gamma, lam
    )
    interpret = jax.default_backend() not in ("tpu", "axon")
    adv = segment_scan_reverse(a, b, interpret=interpret)
    return _finish_gae(adv, values32, valid)


def resolve_gae_impl(impl: str, r: int, t: int) -> str:
    """Resolve 'auto' to a concrete impl for the given packed shape
    (trace-time static decision, mirroring ops/attention.
    resolve_attn_impl). Explicit values pass through untouched.

    'auto' is the associative scan everywhere: it beats the serial scan
    on CPU (measured 2x at [8, 4096]) and avoids T dependent dispatches
    on TPU. The Pallas kernel stays opt-in (impl='pallas') until a
    device window banks kernel_micro_gae crossover evidence — flipping
    a default on unmeasured kernel timings is how CPU-proxy numbers get
    conflated with chip numbers."""
    if impl != "auto":
        return impl
    return "assoc"


def packed_gae(
    rewards: jnp.ndarray,
    values: jnp.ndarray,
    segment_ids: jnp.ndarray,
    bootstrap: jnp.ndarray,
    gamma: float = 1.0,
    lam: float = 1.0,
    impl: str = "auto",
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Dispatch between GAE implementations (static, trace-time):
    'scan' (the serial oracle), 'assoc', 'pallas', or 'auto'
    (resolve_gae_impl). The PPO interface calls this with the
    AREAL_GAE_IMPL knob's value."""
    impl = resolve_gae_impl(impl, rewards.shape[0], rewards.shape[1])
    if impl == "scan":
        return gae_rows(rewards, values, segment_ids, bootstrap,
                        gamma=gamma, lam=lam)
    if impl == "assoc":
        return gae_rows_assoc(rewards, values, segment_ids, bootstrap,
                              gamma=gamma, lam=lam)
    if impl == "pallas":
        return gae_rows_pallas(rewards, values, segment_ids, bootstrap,
                               gamma=gamma, lam=lam)
    raise ValueError(
        f"unknown gae impl {impl!r}; expected 'auto', 'scan', 'assoc', "
        f"or 'pallas'"
    )

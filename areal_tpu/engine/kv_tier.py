"""Tiered KV store: host-RAM (+ optional local-disk) home for parked
prefixes that no longer fit HBM.

The fleet-wide KV plane's storage layer (ROADMAP item 2, the weight
plane's shape applied to KV): the serving engine's prefix-eviction path
SPILLS a parked prefix here — in the existing ``areal-kv-handoff/v1``
blob format (engine/kv_handoff.py), content-hashed per chunk — instead
of freeing it, and a returning session restores the prefix through the
normal ``import_kv_handoff`` scatter path instead of paying a full
re-prefill. HBM holds the active set; this store holds the long tail.

Two tiers:

- **host**: an LRU of (meta, payload) pairs bounded by
  ``host_capacity_bytes`` of payload;
- **disk** (optional): host-LRU evictions demote into ``disk_dir``
  (meta json + payload bin per entry, content-addressed filenames),
  bounded by ``disk_capacity_bytes``; a disk read re-verifies every
  chunk hash before the entry is trusted (a torn/corrupted file is
  dropped and counted, never imported).

Entries are keyed by qid and carry the prefix content hash
(kv_handoff.prefix_content_hash) plus the weight version they were
computed under — the manager's global prefix index serves from
``held()``. The store never touches jax: payloads are opaque bytes in
the handoff wire format, so the server can serve them to peers
(``/kv/{manifest,chunk}``) without a device round trip.

Thread-safe: the engine's spill thread writes, server executor threads
read/serve, one lock serializes all of it (entries are MB-scale; the
hold times are dict moves and small-file I/O).
"""

from __future__ import annotations

import collections
import hashlib
import json
import os
import threading
from typing import Dict, List, Optional, Tuple

from areal_tpu.base import logging
from areal_tpu.base.chunking import chunk_spans, hash_chunk
from areal_tpu.base.wire_schemas import KV_TIER_V1

logger = logging.getLogger("kv_tier")

TIER_SCHEMA = KV_TIER_V1


class _Entry:
    __slots__ = ("meta", "payload", "n_bytes", "path")

    def __init__(self, meta: Dict, payload: Optional[bytes],
                 n_bytes: int, path: Optional[str] = None):
        self.meta = meta
        self.payload = payload  # None when demoted to disk
        self.n_bytes = n_bytes
        self.path = path  # disk payload path when demoted

    @property
    def tier(self) -> str:
        return "host" if self.payload is not None else "disk"


def verify_payload(meta: Dict, payload: bytes) -> bool:
    """Re-hash a payload against its handoff chunk index (the authority
    rule: the hash, not the filesystem, decides whether bytes are the
    prefix). Cheap relative to the device scatter it gates."""
    index = meta.get("chunks") or {}
    if len(payload) != int(index.get("total_bytes", -1)):
        return False
    cb = int(index.get("chunk_bytes") or 1)
    hashes = index.get("hashes") or []
    for i, (off, ln) in enumerate(chunk_spans(len(payload), cb)):
        if i >= len(hashes) or hash_chunk(payload[off: off + ln]) != hashes[i]:
            return False
    return True


class KVTierStore:
    """LRU host-RAM KV tier with an optional local-disk second tier."""

    def __init__(
        self,
        host_capacity_bytes: int,
        disk_dir: Optional[str] = None,
        disk_capacity_bytes: int = 1 << 30,
    ):
        assert host_capacity_bytes > 0, "use None/0 upstream to disable"
        self.host_capacity = int(host_capacity_bytes)
        self.disk_dir = disk_dir
        self.disk_capacity = int(disk_capacity_bytes)
        if disk_dir:
            os.makedirs(disk_dir, exist_ok=True)
        self._lock = threading.Lock()
        # qid -> _Entry, LRU order (oldest first). Host and disk entries
        # share one map — the tier is per entry, not per map, so a
        # promote/demote is a field flip, not a cross-map move.
        self._entries: "collections.OrderedDict[str, _Entry]" = (
            collections.OrderedDict()
        )
        self._host_bytes = 0
        self._disk_bytes = 0
        # Telemetry (per-tier hit/miss/bytes — the /metrics surface).
        self.host_hits = 0
        self.disk_hits = 0
        self.misses = 0
        self.put_total = 0
        self.put_bytes = 0
        self.demoted_to_disk = 0
        self.dropped_capacity = 0
        self.dropped_corrupt = 0

    # -- internals (call under self._lock) ------------------------------

    def _disk_path(self, qid: str, meta: Dict) -> str:
        key = hashlib.sha256(
            f"{qid}:{meta.get('content_hash', '')}".encode()
        ).hexdigest()[:24]
        return os.path.join(self.disk_dir, key)

    def _unlink(self, path: str):
        for suffix in (".bin", ".json"):
            try:
                os.unlink(path + suffix)
            except OSError:
                pass

    def _drop(self, qid: str, ent: _Entry, corrupt: bool = False):
        self._entries.pop(qid, None)
        if ent.payload is not None:
            self._host_bytes -= ent.n_bytes
        else:
            self._disk_bytes -= ent.n_bytes
        if ent.path is not None:
            self._unlink(ent.path)
        if corrupt:
            self.dropped_corrupt += 1

    def _demote_or_drop(self, qid: str, ent: _Entry):
        """Host-tier eviction: demote to disk when configured, else the
        prefix is gone for good (the restore path re-prefills)."""
        assert ent.payload is not None
        self._host_bytes -= ent.n_bytes
        if self.disk_dir is None:
            self._entries.pop(qid, None)
            self.dropped_capacity += 1
            return
        path = self._disk_path(qid, ent.meta)
        try:
            with open(path + ".bin", "wb") as f:
                f.write(ent.payload)
            with open(path + ".json", "w") as f:
                json.dump(ent.meta, f)
        except OSError:
            logger.warning(f"kv tier: disk demotion failed for {qid!r}",
                           exc_info=True)
            self._entries.pop(qid, None)
            self._unlink(path)
            self.dropped_capacity += 1
            return
        ent.payload = None
        ent.path = path
        self._disk_bytes += ent.n_bytes
        self.demoted_to_disk += 1
        # Disk tier has its own LRU bound (oldest disk entries go).
        while self._disk_bytes > self.disk_capacity:
            victim = next(
                (q for q, e in self._entries.items()
                 if e.payload is None and q != qid),
                None,
            )
            if victim is None:
                break
            self._drop(victim, self._entries[victim])
            self.dropped_capacity += 1

    def _trim_host(self, keep: Optional[str] = None):
        while self._host_bytes > self.host_capacity:
            victim = next(
                (q for q, e in self._entries.items()
                 if e.payload is not None and q != keep),
                None,
            )
            if victim is None:
                break
            self._demote_or_drop(victim, self._entries[victim])

    # -- public API ------------------------------------------------------

    def put(self, qid: str, meta: Dict, payload: bytes):
        """Insert/replace a spilled prefix (host tier), LRU-evicting
        (demoting) over capacity. Oversized single entries demote/drop
        immediately rather than wedging the whole tier."""
        with self._lock:
            old = self._entries.get(qid)
            if old is not None:
                self._drop(qid, old)
            ent = _Entry(meta, payload, len(payload))
            self._entries[qid] = ent
            self._host_bytes += ent.n_bytes
            self.put_total += 1
            self.put_bytes += ent.n_bytes
            self._trim_host()

    def get(self, qid: str,
            count: bool = True) -> Optional[Tuple[Dict, bytes, str]]:
        """(meta, payload, tier-it-was-found-in) or None. A disk hit is
        hash-verified and promoted back to the host tier; corruption
        drops the entry (counted) and reads as a miss. ``count=False``
        skips hit/miss accounting (peer chunk serving probes the same
        entry once per chunk — that is one logical hit, not dozens)."""
        with self._lock:
            ent = self._entries.get(qid)
            if ent is None:
                if count:
                    self.misses += 1
                return None
            if ent.payload is not None:
                self._entries.move_to_end(qid)
                if count:
                    self.host_hits += 1
                return ent.meta, ent.payload, "host"
            try:
                with open(ent.path + ".bin", "rb") as f:
                    payload = f.read()
            except OSError:
                self._drop(qid, ent, corrupt=True)
                if count:
                    self.misses += 1
                return None
            if not verify_payload(ent.meta, payload):
                logger.warning(
                    f"kv tier: corrupted disk entry for {qid!r}; dropped"
                )
                self._drop(qid, ent, corrupt=True)
                if count:
                    self.misses += 1
                return None
            # Promote: disk -> host (the entry is hot again).
            self._disk_bytes -= ent.n_bytes
            self._unlink(ent.path)
            ent.path = None
            ent.payload = payload
            self._host_bytes += ent.n_bytes
            self._entries.move_to_end(qid)
            self._trim_host(keep=qid)
            if count:
                self.disk_hits += 1
            return ent.meta, ent.payload, "disk"

    def peek_tier(self, qid: str) -> Optional[str]:
        """Which tier holds qid (no hit accounting, no promotion)."""
        with self._lock:
            ent = self._entries.get(qid)
            return None if ent is None else ent.tier

    def peek_meta(self, qid: str,
                  count_miss: bool = False) -> Optional[Dict]:
        """The entry's meta without touching the payload: metas stay in
        host memory even for disk-demoted entries, so callers can
        validate (prompt prefix, version) BEFORE paying a disk read /
        promotion / hit count — a rejected probe must not churn the
        LRU or overstate tier effectiveness."""
        with self._lock:
            ent = self._entries.get(qid)
            if ent is None:
                if count_miss:
                    self.misses += 1
                return None
            return ent.meta

    def discard(self, qid: str):
        with self._lock:
            ent = self._entries.get(qid)
            if ent is not None:
                self._drop(qid, ent)

    def clear(self):
        """Drop everything — the weight-swap path: spilled KV from the
        old version can never be restored under the new weights."""
        with self._lock:
            for qid in list(self._entries):
                self._drop(qid, self._entries[qid])

    def held(self, cap: int = 8192) -> List[Dict]:
        """Index view for the manager's global prefix index: newest-
        first, bounded (a million-session tail doesn't belong in one
        poll response — the oldest entries are the next to age out
        anyway)."""
        with self._lock:
            out = []
            for qid in reversed(self._entries):
                if len(out) >= cap:
                    break
                ent = self._entries[qid]
                out.append({
                    "qid": qid,
                    "tier": ent.tier,
                    "n_tokens": int(ent.meta.get("n_tokens", 0)),
                    "content_hash": ent.meta.get("content_hash", ""),
                    "version": int(ent.meta.get("version", -1)),
                })
            return out

    def stats(self) -> Dict[str, float]:
        with self._lock:
            n_host = sum(
                1 for e in self._entries.values() if e.payload is not None
            )
            return {
                "host_entries": float(n_host),
                "disk_entries": float(len(self._entries) - n_host),
                "host_bytes": float(self._host_bytes),
                "disk_bytes": float(self._disk_bytes),
                "host_hits": float(self.host_hits),
                "disk_hits": float(self.disk_hits),
                "misses": float(self.misses),
                "put_total": float(self.put_total),
                "put_bytes": float(self.put_bytes),
                "demoted_to_disk": float(self.demoted_to_disk),
                "dropped_capacity": float(self.dropped_capacity),
                "dropped_corrupt": float(self.dropped_corrupt),
            }

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

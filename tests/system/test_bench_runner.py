"""Bench runner under chaos: a wedged or killed phase subprocess costs
one phase, never the bank. Covers the acceptance flow — simulated flap
mid-phase leaves a valid bank, a restart completes only the unbanked
phases, and the report the bank yields validates clean."""

import json
import os
import subprocess
import sys

import pytest

from areal_tpu.bench import bank, phases, report, runner
from tests.fixtures import scale_timeout
from tests.system.bench_phases import read_counter

pytestmark = pytest.mark.chaos

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.fixture
def bench_env(tmp_path, monkeypatch):
    scratch = tmp_path / "scratch"
    scratch.mkdir()
    b = str(tmp_path / "bank")
    monkeypatch.setenv("AREAL_BENCH_BANK", b)
    monkeypatch.setenv("AREAL_BENCH_TEST_SCRATCH", str(scratch))
    monkeypatch.setenv("AREAL_BENCH_PHASE_MODULES", "tests.system.bench_phases")
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    # Subprocess jax imports are pure overhead here: keep them 1-device.
    monkeypatch.setenv("XLA_FLAGS", "")
    yield b, str(scratch)


def test_ok_phase_banks_attested_record(bench_env):
    b, scratch = bench_env
    rec = runner.run_phase("t_alpha", "measure", b,
                           deadline_s=scale_timeout(120))
    assert rec["status"] == "ok"
    assert rec["value"] == {"alpha_metric": 42.0}
    assert rec["attestation"]["platform"] == "cpu"
    assert rec["attestation"]["driver_verified"] is False
    assert read_counter(scratch, "t_alpha.measure") == 1
    # The banked file is the same validated record.
    on_disk = bank.load_record(b, "t_alpha", "measure")
    assert on_disk["value"] == rec["value"]


def test_phase_exception_banked_as_failure(bench_env):
    b, _ = bench_env
    rec = runner.run_phase("t_broken", "measure", b,
                           deadline_s=scale_timeout(120))
    assert rec["status"] == "failed"
    assert "always fails" in (rec["error"] or "") + (rec["tail"] or "")
    assert rec["value"] is None
    bank.validate_record(rec)  # a failure is still well-formed evidence
    assert not bank.is_banked(b, "t_broken", "measure", "cpu")


def test_crashed_subprocess_banked_by_parent(bench_env, monkeypatch):
    """A hard kill (PJRT-crash stand-in: fault action 'die' = os._exit)
    leaves no child-written record; the parent banks the failure with
    the captured output tail."""
    b, scratch = bench_env
    monkeypatch.setenv("AREAL_FAULTS", "bench.runner.phase@bench/t_alpha=die")
    rec = runner.run_phase("t_alpha", "measure", b,
                           deadline_s=scale_timeout(120))
    assert rec["status"] == "failed"
    assert "exited" in rec["error"]
    # The fault fired before the phase body ran.
    assert read_counter(scratch, "t_alpha.measure") == 0
    bank.validate_record(bank.load_record(b, "t_alpha", "measure"))


def test_parent_failure_never_clobbers_child_ok_record(bench_env,
                                                       monkeypatch):
    """A child that atomically banks its ok record and THEN wedges/dies
    (teardown hung on the dying tunnel) must not have the completed
    measurement overwritten by the parent's failure bookkeeping."""
    b, _ = bench_env
    # Stand-in for "child banked ok, then died": the record exists and is
    # fresh when the parent observes a crashed child.
    bank.write_record(bank.make_record(
        "t_alpha", "measure", "ok", value={"alpha_metric": 42.0}), b)
    monkeypatch.setenv("AREAL_FAULTS", "bench.runner.phase@bench/t_alpha=die")
    rec = runner.run_phase("t_alpha", "measure", b,
                           deadline_s=scale_timeout(120))
    assert rec["status"] == "ok"
    assert rec["value"] == {"alpha_metric": 42.0}
    assert bank.load_record(b, "t_alpha", "measure")["status"] == "ok"


def test_wedged_subprocess_killed_at_deadline(bench_env, monkeypatch):
    """A hang (wedged-XLA-compile stand-in) is killed at the phase
    deadline and banked as a timeout — the failure mode that lost the
    round-5 tunnel window can now cost at most one phase."""
    b, _ = bench_env
    monkeypatch.setenv("AREAL_FAULTS", "bench.runner.phase@bench/t_slow=hang")
    rec = runner.run_phase("t_slow", "measure", b,
                           deadline_s=scale_timeout(15))
    assert rec["status"] == "timeout"
    assert "deadline" in rec["error"]
    bank.validate_record(bank.load_record(b, "t_slow", "measure"))


def test_flap_then_restart_completes_only_unbanked(bench_env, monkeypatch):
    """Acceptance flow: kill one phase mid-run (chaos hook), assert the
    bank survived, then re-run and assert only the unbanked phase
    executed; the report built from the bank validates clean, every
    record carrying an attestation block."""
    import bench

    b, scratch = bench_env
    specs = [phases.get("t_alpha"), phases.get("t_beta")]

    # Run 1: t_beta's subprocess is killed mid-phase (simulated flap).
    monkeypatch.setenv("AREAL_FAULTS", "bench.runner.phase@bench/t_beta=die")
    monkeypatch.setenv("AREAL_BENCH_PHASE_DEADLINE_S", str(scale_timeout(120)))
    assert bench.run_oneshot(specs, b, "cpu") is False
    assert bank.is_banked(b, "t_alpha", "measure", "cpu")
    assert not bank.is_banked(b, "t_beta", "measure", "cpu")
    assert read_counter(scratch, "t_alpha.measure") == 1

    # Run 2: no faults; only t_beta may execute.
    monkeypatch.delenv("AREAL_FAULTS")
    assert bench.run_oneshot(specs, b, "cpu") is True
    assert read_counter(scratch, "t_alpha.compile") == 1
    assert read_counter(scratch, "t_alpha.measure") == 1
    assert read_counter(scratch, "t_beta.compile") == 1
    assert read_counter(scratch, "t_beta.measure") == 1

    # Every banked record (incl. run 1's failure overwritten by run 2's
    # ok) is schema-valid with an attestation block.
    records = bank.load_bank(b)
    assert {("t_alpha", "measure"), ("t_beta", "measure"),
            ("t_alpha", "compile"), ("t_beta", "compile")} <= set(records)
    for rec in records.values():
        bank.validate_record(rec)
        assert rec["attestation"]["driver_verified"] is False

    # Report + driver-line + validator (the scripts/ entry points).
    out = str(os.path.join(scratch, "BENCH_test.json"))
    proc = subprocess.run(
        [sys.executable, "scripts/bench_report.py", "--bank", b,
         "--out", out, "--round", "rtest"],
        cwd=REPO, capture_output=True, text=True,
        timeout=scale_timeout(120),
    )
    assert proc.returncode == 0, proc.stderr
    rep = json.loads(open(out).read())
    assert rep["schema"] == bank.REPORT_SCHEMA
    assert rep["round"] == "rtest"
    assert rep["driver_verified"] is False
    proc = subprocess.run(
        [sys.executable, "scripts/validate_bench.py", out],
        cwd=REPO, capture_output=True, text=True,
        timeout=scale_timeout(60),
    )
    assert proc.returncode == 0, proc.stderr


def _load_validator():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "validate_bench", os.path.join(REPO, "scripts", "validate_bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_validator_rejects_tampered_evidence(bench_env):
    """Failures on malformed records and on headline numbers that lack
    driver_verified: true without the proxy label (the conflation the
    attestation block exists to prevent)."""
    b, _ = bench_env
    runner.run_phase("t_alpha", "measure", b, deadline_s=scale_timeout(120))
    rep = report.build_report(b)
    validator = _load_validator()

    assert validator.validate_report(rep) == []

    # Inject an unlabeled CPU headline number: conflation, must fail.
    bad = json.loads(json.dumps(rep))
    bad["headline"]["alpha_metric"] = {"value": 42.0,
                                       "driver_verified": False}
    assert any("conflate" in p for p in validator.validate_report(bad))

    # Strip an attestation block: malformed record, must fail.
    bad = json.loads(json.dumps(rep))
    del bad["phases"]["t_alpha"]["attestation"]
    assert validator.validate_report(bad)

    # A report claiming driver_verified its records don't back: fail.
    bad = json.loads(json.dumps(rep))
    bad["driver_verified"] = True
    assert validator.validate_report(bad)

    # --require-driver-verified gates CPU evidence out of a chip round.
    ok_proxy = json.loads(json.dumps(rep))
    ok_proxy["headline"]["x"] = {
        "value": 1.0, "driver_verified": False, "evidence": "proxy",
    }
    assert validator.validate_report(ok_proxy, require_driver=False) == []
    assert validator.validate_report(ok_proxy, require_driver=True)


def test_weight_update_phase_survives_peer_chaos_and_validates(
    bench_env, monkeypatch
):
    """ISSUE 5 CI satellite: the weight_update phase, run through the
    subprocess runner with AREAL_FAULTS killing a mid-transfer peer
    (the middle holder of the chain dies serving its child), must
    re-fanout from the SURVIVING peer, bank an ok record that still
    carries the O(1)-origin-egress invariant, and leave a bank + report
    that validate clean."""
    import pytest as _pytest

    b, _ = bench_env
    # The phase moves a 16 MiB payload in 1 MiB chunks along a 3-holder
    # chain; waves are strictly ordered, so /weights/chunk hits 33-48
    # are h1 serving h2. Fire all 3 retry attempts of h2's chunk 7:
    # h1 "dies" mid-serve and h2 must re-fanout from h0, not the origin.
    monkeypatch.setenv(
        "AREAL_FAULTS", "weight_plane.serve_chunk=raise:k=40:n=3"
    )
    rec = runner.run_phase(
        "weight_update", "measure", b, deadline_s=scale_timeout(300)
    )
    monkeypatch.delenv("AREAL_FAULTS")
    assert rec["status"] == "ok", rec
    val = rec["value"]
    # Re-fanout went peer-to-peer: the origin still egressed exactly
    # one payload, and the transfer/cutover split is intact.
    assert val["origin_full_payloads"] == _pytest.approx(1.0)
    assert val["weight_transfer_ms"] > 0.0
    assert val["weight_cutover_ms"] > 0.0
    assert val["weight_update_ms"] >= val["weight_transfer_ms"]

    validator = _load_validator()
    assert validator.validate_bank_dir(b) == []
    rep = report.build_report(b)
    assert validator.validate_report(rep) == []

    # The validator's schema coverage has teeth: strip a required key /
    # degrade the invariant and the same record must now fail.
    tampered = json.loads(json.dumps(rec))
    del tampered["value"]["weight_cutover_ms"]
    assert any(
        "weight_cutover_ms" in p
        for p in validator.validate_phase_value("weight_update", tampered)
    )
    degraded = json.loads(json.dumps(rec))
    degraded["value"]["origin_full_payloads"] = 3.0
    assert any(
        "broadcast" in p
        for p in validator.validate_phase_value("weight_update", degraded)
    )

"""ISSUE 20 acceptance (bench leg): the `multi_model_serving` phase
banks an attested CPU-proxy record for the multi-model serving plane —
two model families on one real-process fleet, per-model greedy parity
vs single-model baseline fleets, unknown-model refusal, cross-model KV
isolation, and an independent weight cutover of one family under the
other's sustained load — and `validate_bench.py` refuses records with
contaminated parity, any cross-model route/KV hit, a steady pool whose
version or outputs moved during the other family's cutover, or
B-degradation during the A-cutover.

Time budget (slow lane): ~300 s — three fleets (two single-model
baselines + the 3-server multi-model fleet) and two weight fanouts.
Tier-1 keeps the validator-teeth test (milliseconds).
"""

import importlib.util
import json
import os

import pytest

from areal_tpu.bench import bank

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

pytestmark = pytest.mark.serial


def _load_validator():
    spec = importlib.util.spec_from_file_location(
        "validate_bench", os.path.join(REPO, "scripts", "validate_bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _fake_record():
    """A well-formed multi_model_serving value (what a healthy run
    banks)."""
    return {
        "n_models": 2.0,
        "steady_pool_servers": 2.0,
        "cutover_pool_servers": 1.0,
        "families_distinct": 1.0,
        "parity_mismatches": 0.0,
        "cross_model_routes": 0.0,
        "cross_model_kv_hits": 0.0,
        "unknown_model_rejected": 1.0,
        "unknown_model_routed": 0.0,
        "cutover_version_before": 1.0,
        "cutover_version_after": 2.0,
        "steady_version_after": 1.0,
        "steady_outputs_stable": 1.0,
        "cutover_outputs_changed": 1.0,
        "b_completed": 16.0,
        "b_failed": 0.0,
        "b_p99_ttft_base_ms": 120.0,
        "b_p99_ttft_cutover_ms": 150.0,
        "kv_prefix_lost": 0.0,
        "fleet": "process",
        "wall_s": 200.0,
    }


def test_validator_teeth_for_multi_model_serving():
    """Tier-1 guard: the schema refuses records that could launder a
    leaky model boundary into multi-model evidence."""
    validator = _load_validator()
    rec = {"status": "ok", "pass": "measure", "value": _fake_record()}
    assert validator.validate_phase_value("multi_model_serving", rec) == []

    def probs(**edits):
        bad = json.loads(json.dumps(rec))
        bad["value"].update(edits)
        for k, v in list(edits.items()):
            if v is None:
                del bad["value"][k]
        return validator.validate_phase_value("multi_model_serving", bad)

    # Contaminated parity or any cross-model hit poisons the record.
    assert any("baseline" in p for p in probs(parity_mismatches=1.0))
    assert any("pool" in p for p in probs(cross_model_routes=1.0))
    assert any("KV source" in p for p in probs(cross_model_kv_hits=1.0))
    # The unknown-model negative arm must have run AND refused.
    assert any("refused" in p for p in probs(unknown_model_routed=1.0))
    assert any(
        "negative arm" in p for p in probs(unknown_model_rejected=0.0)
    )
    # Independence: the cutover family advances, the steady family's
    # version and outputs do not move, and identical config hashes are
    # refused outright.
    assert any(
        "never advanced" in p for p in probs(cutover_version_after=1.0)
    )
    assert any(
        "steady pool" in p for p in probs(steady_version_after=2.0)
    )
    assert any(
        "contamination" in p for p in probs(steady_outputs_stable=0.0)
    )
    assert any(
        "never actually swapped" in p
        for p in probs(cutover_outputs_changed=0.0)
    )
    assert any("hash" in p for p in probs(families_distinct=0.0))
    # The B side must be loss-free and hold its tail across the
    # A-cutover.
    assert any("failed" in p for p in probs(b_failed=1.0))
    assert any("nothing was measured" in p for p in probs(b_completed=0.0))
    assert any(
        "stalled" in p for p in probs(b_p99_ttft_cutover_ms=100000.0)
    )
    assert any("prefix" in p for p in probs(kv_prefix_lost=1.0))
    # Missing required numerics.
    assert any(
        "b_p99_ttft_base_ms" in p for p in probs(b_p99_ttft_base_ms=None)
    )


@pytest.mark.slow  # ~300 s: three fleets + two weight fanouts; tier-1
# keeps the validator teeth + the multi-model e2e.
@pytest.mark.timeout(1800)
def test_multi_model_serving_banks_and_validates(tmp_path, monkeypatch):
    b = str(tmp_path / "bank")
    monkeypatch.setenv("AREAL_BENCH_BANK", b)
    from areal_tpu.bench.workloads import multi_model_serving_phase

    val = multi_model_serving_phase("measure")
    path = bank.write_record(
        bank.make_record("multi_model_serving", "measure", "ok", value=val),
        b,
    )
    with open(path) as f:
        rec = json.load(f)
    bank.validate_record(rec)
    assert rec["attestation"]["platform"] == "cpu"
    assert rec["attestation"]["driver_verified"] is False

    validator = _load_validator()
    assert validator.validate_phase_value("multi_model_serving", rec) == []
    assert validator.validate_bank_dir(b) == []

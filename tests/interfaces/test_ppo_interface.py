"""PPO interface integration: generate -> reward -> inference -> train_step
on a tiny model (counterpart of reference tests/experiments/test_math_ppo.py
algorithm core, without the worker system)."""

import jax
import numpy as np
import pytest

from areal_tpu.api.config import ModelName
from areal_tpu.api.data_api import MicroBatchSpec, SequenceSample
from areal_tpu.api.model_api import GenerationHyperparameters, Model
from areal_tpu.engine.jax_engine import JaxTrainEngine
from areal_tpu.engine.optimizer import OptimizerConfig
from areal_tpu.interfaces.ppo import PPOActorInterface, PPOCriticInterface
from areal_tpu.models.config import TransformerConfig
from areal_tpu.models.transformer import init_params


def small_cfg(**kw):
    return TransformerConfig(
        n_layers=2, hidden_dim=32, n_q_heads=4, n_kv_heads=2, head_dim=8,
        intermediate_dim=64, vocab_size=64, compute_dtype="float32", **kw,
    )


def make_actor(lr=1e-3):
    cfg = small_cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))
    eng = JaxTrainEngine(
        cfg, params,
        optimizer_config=OptimizerConfig(lr=lr, warmup_steps_proportion=0.0),
        total_train_steps=100, row_len_multiple=32,
    )
    return Model(name=ModelName("actor"), module=eng, tokenizer=None)


def make_prompts(n=4, seed=0):
    rng = np.random.RandomState(seed)
    lens = rng.randint(3, 8, size=n).tolist()
    return SequenceSample.from_default(
        ids=[f"p{i}" for i in range(n)],
        seqlens=lens,
        data={"packed_prompts": rng.randint(1, 64, size=sum(lens))},
    )


@pytest.fixture(scope="module")
def rollout():
    model = make_actor()
    itf = PPOActorInterface(
        gconfig=GenerationHyperparameters(n=2, max_new_tokens=10, greedy=False),
        n_minibatches=2, adv_norm=True,
    )
    prompts = make_prompts()
    sample = itf.generate(model, prompts, MicroBatchSpec())
    return model, itf, prompts, sample


def test_generate_builds_grouped_sample(rollout):
    model, itf, prompts, sample = rollout
    assert sample.bs == prompts.bs
    assert all(len(sl) == 2 for sl in sample.seqlens["packed_input_ids"])
    total = sample.total_seqlen("packed_input_ids")
    assert sample.data["packed_input_ids"].shape[0] == total
    assert sample.data["prompt_mask"].shape[0] == total
    # Behavior logprobs: zero on prompts (except final prompt position).
    pm = sample.data["prompt_mask"]
    lp = sample.data["packed_logprobs"]
    offset = 0
    for sl in sample.seqlens["packed_input_ids"]:
        for l in sl:
            seq_pm = pm[offset : offset + l]
            seq_lp = lp[offset : offset + l]
            plen = int(seq_pm.sum())
            assert (seq_lp[: plen - 1] == 0).all()
            assert (seq_lp[plen - 1 : l - 1] != 0).any() or l - plen <= 1
            offset += l
    assert sample.data["seq_no_eos_mask"].shape[0] == prompts.bs * 2


def _attach_rewards_and_logps(model, sample, with_critic=False, seed=1):
    rng = np.random.RandomState(seed)
    n_seqs = sum(len(sl) for sl in sample.seqlens["packed_input_ids"])
    sl_tok = [list(s) for s in sample.seqlens["packed_input_ids"]]
    sl_seq = [[1] * len(s) for s in sample.seqlens["packed_input_ids"]]
    total = sample.total_seqlen("packed_input_ids")
    add = SequenceSample(
        ids=list(sample.ids),
        keys={"rewards", "ref_logprobs"},
        data={
            "rewards": rng.choice([5.0, -5.0], size=n_seqs).astype(np.float32),
            "ref_logprobs": (sample.data["packed_logprobs"]
                             + 0.01 * rng.randn(total)).astype(np.float32),
        },
        seqlens={"rewards": sl_seq, "ref_logprobs": sl_tok},
    )
    sample.update_(add)
    if with_critic:
        vals = rng.randn(total).astype(np.float32) * 0.1
        sample.update_(SequenceSample(
            ids=list(sample.ids), keys={"values"},
            data={"values": vals}, seqlens={"values": sl_tok},
        ))


def test_train_step_grpo_mode(rollout):
    model, itf, prompts, sample = rollout
    sample = SequenceSample.gather([sample])  # copy-ish
    _attach_rewards_and_logps(model, sample)
    v0 = model.version
    stats = itf.train_step(model, sample, MicroBatchSpec())
    assert model.version == v0 + 1
    assert np.isfinite(stats["ppo_actor/loss"])
    assert np.isfinite(stats["ppo_actor/kl"])
    assert stats["ppo_actor/n_tokens"] > 0
    assert "ppo_actor/head_offpolicyness" in stats


def test_train_step_decoupled_with_critic(rollout):
    model, _, prompts, sample0 = rollout
    itf = PPOActorInterface(
        gconfig=GenerationHyperparameters(n=2, max_new_tokens=10),
        n_minibatches=2, use_decoupled_loss=True, behav_imp_weight_cap=10.0,
        group_adv_norm=True,
    )
    sample = SequenceSample.gather([sample0])
    _attach_rewards_and_logps(model, sample, with_critic=True, seed=3)
    # Proximal logprobs from the current policy (actor inference MFC).
    prox = itf.inference(model, sample, MicroBatchSpec())
    sample.update_(prox)
    stats = itf.train_step(model, sample, MicroBatchSpec())
    assert np.isfinite(stats["ppo_actor/loss"])
    assert stats["ppo_actor/importance_weight"] > 0


def test_critic_interface_roundtrip(rollout):
    model_actor, _, prompts, sample0 = rollout
    ccfg = small_cfg(is_critic=True)
    cparams = init_params(ccfg, jax.random.PRNGKey(9))
    ceng = JaxTrainEngine(
        ccfg, cparams,
        optimizer_config=OptimizerConfig(lr=1e-3, warmup_steps_proportion=0.0),
        total_train_steps=100, row_len_multiple=32,
    )
    cmodel = Model(name=ModelName("critic"), module=ceng, tokenizer=None)
    citf = PPOCriticInterface(n_minibatches=2)

    sample = SequenceSample.gather([sample0])
    vals = citf.inference(cmodel, sample, MicroBatchSpec())
    assert vals.keys == {"values"}
    sample.update_(vals)
    _attach_rewards_and_logps(cmodel, sample, seed=5)
    stats = citf.train_step(cmodel, sample, MicroBatchSpec())
    assert np.isfinite(stats["ppo_critic/loss"])


# ---------------------------------------------------------------------------
# Best-of-k selection (generation_size > group_size), reference
# ppo_interface.py:376-408
# ---------------------------------------------------------------------------


class _StubGenEngine:
    """Returns handcrafted candidates so selection is fully determined."""

    def __init__(self, outs):
        self.outs = outs
        self.last_gconfig = None

    def generate(self, input_, mb_spec, tokenizer, gconfig):
        self.last_gconfig = gconfig
        return self.outs


class _StubTokenizer:
    def __init__(self, mapping):
        self.mapping = {tuple(k): v for k, v in mapping}

    def decode(self, ids):
        return self.mapping[tuple(ids)]


def _cand(ids, text):
    return (
        dict(
            output_ids=list(ids),
            output_logprobs=np.full(len(ids), -0.5, np.float32),
            no_eos=False,
        ),
        (list(ids), text),
    )


def test_best_of_k_selects_verified_candidates():
    """With generation_size=4 and n=2, only the two verified-correct
    candidates survive into the training sample (longer first)."""
    cands = [
        _cand([1, 2, 3], "the answer is \\boxed{41}"),      # wrong, len 3
        _cand([4, 5], "\\boxed{42}"),                        # right, len 2
        _cand([6, 7, 8, 9, 10], "no answer here at all"),    # wrong, len 5
        _cand([11, 12, 13, 14], "so \\boxed{42} indeed"),    # right, len 4
    ]
    outs = [c[0] for c in cands]
    eng = _StubGenEngine(outs)
    tok = _StubTokenizer([c[1] for c in cands])
    model = Model(name=ModelName("actor"), module=eng, tokenizer=tok)
    itf = PPOActorInterface(
        gconfig=GenerationHyperparameters(n=2, max_new_tokens=8),
        generation_size=4,
    )
    prompts = SequenceSample.from_default(
        ids=["p0"],
        seqlens=[3],
        data={"packed_prompts": np.asarray([50, 51, 52])},
        metadata=dict(tasks=["math"], solutions=[["\\boxed{42}"]]),
    )
    sample = itf.generate(model, prompts, MicroBatchSpec())

    # The engine was asked for generation_size candidates...
    assert eng.last_gconfig.n == 4
    # ...but the sample holds only n=2 groups.
    group_lens = sample.seqlens["packed_input_ids"][0]
    assert len(group_lens) == 2
    flat = np.asarray(sample.data["packed_input_ids"])
    seqs = np.split(flat, np.cumsum(group_lens))[:-1]
    # Correct candidates only, longer one first (score desc, length desc).
    assert seqs[0].tolist() == [50, 51, 52, 11, 12, 13, 14]
    assert seqs[1].tolist() == [50, 51, 52, 4, 5]


def test_best_of_k_all_wrong_falls_back_to_longest():
    cands = [
        _cand([1], "nope"),
        _cand([2, 3, 4], "still nope"),
        _cand([5, 6], "wrong"),
    ]
    eng = _StubGenEngine([c[0] for c in cands])
    tok = _StubTokenizer([c[1] for c in cands])
    model = Model(name=ModelName("actor"), module=eng, tokenizer=tok)
    itf = PPOActorInterface(
        gconfig=GenerationHyperparameters(n=1, max_new_tokens=8),
        generation_size=3,
    )
    prompts = SequenceSample.from_default(
        ids=["p0"],
        seqlens=[2],
        data={"packed_prompts": np.asarray([50, 51])},
        metadata=dict(tasks=["math"], solutions=[["\\boxed{42}"]]),
    )
    sample = itf.generate(model, prompts, MicroBatchSpec())
    flat = np.asarray(sample.data["packed_input_ids"])
    # Tie on score=0 -> longest generation wins.
    assert flat.tolist() == [50, 51, 2, 3, 4]


def test_best_of_k_requires_solutions_metadata():
    eng = _StubGenEngine([])
    model = Model(name=ModelName("actor"), module=eng, tokenizer=_StubTokenizer([]))
    itf = PPOActorInterface(
        gconfig=GenerationHyperparameters(n=1), generation_size=2
    )
    prompts = make_prompts(n=1)
    with pytest.raises(ValueError, match="solutions"):
        itf.generate(model, prompts, MicroBatchSpec())

"""Experiment definitions: option dataclasses -> worker configs + MFC graph.

Counterpart of the reference's experiments layer (realhf/experiments/):
each experiment class is a pure function from its cli_args dataclass to
an `ExperimentConfig` (worker configs + DFG), registered by name.
"""

from areal_tpu.api.config import Registry

EXPERIMENT_REGISTRY = Registry("experiment")


def register_experiment(name: str, builder):
    EXPERIMENT_REGISTRY.register(name, builder)


def make_experiment(name: str, cfg):
    return EXPERIMENT_REGISTRY.make(name, cfg)


from areal_tpu.experiments import sft_exp, ppo_math_exp, async_ppo_math_exp  # noqa: E402,F401

"""Device-resident decode state (ISSUE 15 tentpole, decode front).

AREAL_DECODE_RESIDENT keeps per-slot decode control device-resident
between blocks: page-table edits land as ONE donated per-slot row
scatter (paged.update_page_rows) and chunked-prefill control crosses as
ONE fused int32 array (paged.paged_chunk_prefill_packed), so only
admission/eviction deltas pay H2D. These tests pin:

- greedy-token parity resident vs legacy (the pre-change engine path,
  kept verbatim behind the knob) across chunked prefill, prefix-cache
  resubmission, and multi-round admission;
- the measured reduction itself: per-decode-block H2D transfer count
  strictly below legacy on a chunked workload (the evidence the
  kernel_micro_decode_state phase banks);
- unit semantics of the fused row scatter and the packed chunk-prefill
  entry point against their legacy equivalents.

Time budget: tiny 2-layer CPU engines; whole module well under 30 s
warm (the heaviest test runs two engines over 6 short requests).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from areal_tpu.engine.serving import GenRequest, ServingEngine
from areal_tpu.models.transformer import init_params

from .serving_utils import TINY_SERVING_CFG, run_requests

CFG = TINY_SERVING_CFG


@pytest.fixture(scope="module")
def tiny_params():
    return init_params(CFG, jax.random.PRNGKey(0))


def _engine(params, resident: bool, **kw):
    defaults = dict(
        max_batch_size=2,
        max_seq_len=128,
        decode_block_steps=4,
        prompt_bucket=8,
        page_size=8,
        prefill_chunk=16,
        prefix_cache_tokens=256,
        seed=11,
        decode_resident=resident,
    )
    defaults.update(kw)
    eng = ServingEngine(CFG, params, **defaults)
    eng.start()
    return eng


def _prompts(n=6, seed=0):
    rng = np.random.RandomState(seed)
    # Mix: short (bucketed path), long (chunked path), odd lengths
    # (misaligned pages) — and more requests than slots, forcing
    # multi-round admission + finish/admit page-table churn.
    lens = [5, 17, 40, 9, 33, 26][:n]
    return [rng.randint(6, CFG.vocab_size, size=l).tolist() for l in lens]


def _run(eng, prompts, tag="q", max_new=20):
    reqs = [
        GenRequest(qid=f"{tag}{i}", input_ids=p, max_new_tokens=max_new,
                   greedy=True)
        for i, p in enumerate(prompts)
    ]
    res = run_requests(eng, reqs, timeout=240)
    return {q: r.output_ids for q, r in res.items()}


def test_greedy_parity_and_h2d_reduction(tiny_params):
    """The engine must emit IDENTICAL greedy tokens with the knob on and
    off, while the resident arm stages measurably fewer transfers per
    decode block (strict <: the whole point of the pass) and no more
    bytes. Covers chunked prefill (17/33/40-token prompts over chunk
    16), multi-round admission (6 requests over 2 slots), and the
    finish/admit page-table churn between blocks."""
    prompts = _prompts()
    stats = {}
    outs = {}
    for resident in (True, False):
        eng = _engine(tiny_params, resident)
        try:
            outs[resident] = _run(eng, prompts)
            blocks = max(1, eng.decode_blocks)
            stats[resident] = (
                eng.h2d_transfers / blocks,
                eng.h2d_bytes / blocks,
            )
        finally:
            eng.stop()
    assert outs[True] == outs[False], "resident mode changed greedy tokens"
    assert all(len(v) == 20 for v in outs[True].values())
    assert stats[True][0] < stats[False][0], (
        f"resident h2d/block {stats[True][0]:.2f} not below legacy "
        f"{stats[False][0]:.2f}"
    )
    assert stats[True][1] <= stats[False][1] * 1.05


def test_prefix_cache_resubmission_parity(tiny_params):
    """A same-qid resubmission extending its prompt (the partial-rollout
    protocol) admits through the cache-hit delta prefill — the path the
    packed control array changed most. Tokens must match legacy."""
    rng = np.random.RandomState(7)
    base = rng.randint(6, CFG.vocab_size, size=24).tolist()
    outs = {}
    for resident in (True, False):
        eng = _engine(tiny_params, resident)
        try:
            first = _run(eng, [base], tag="s", max_new=12)["s0"]
            # Resubmit prompt + emitted tokens under the SAME qid: the
            # parked prefix serves all but the 1-token delta.
            second = _run(eng, [base + first], tag="s", max_new=8)["s0"]
            outs[resident] = (first, second)
            assert eng.prefix_cache_hits >= 1
        finally:
            eng.stop()
    assert outs[True] == outs[False]


def test_update_page_rows_matches_full_restage():
    """Unit pin: scattering dirty rows into a device-resident table
    yields exactly the table a full restage would build; padding rows
    (slot < 0) must not write anywhere."""
    from areal_tpu.engine.paged import update_page_rows

    rng = np.random.RandomState(0)
    B, P = 8, 6
    host = rng.randint(0, 50, size=(B, P)).astype(np.int32)
    dev = jnp.asarray(host)
    # Mutate three rows + build the packed [m, P+1] control (pow2 pad).
    host[1] = rng.randint(0, 50, size=P)
    host[4] = rng.randint(0, 50, size=P)
    host[6] = rng.randint(0, 50, size=P)
    packed = np.full((4, P + 1), -1, np.int32)
    for i, slot in enumerate((1, 4, 6)):
        packed[i, 0] = slot
        packed[i, 1:] = host[slot]
    packed[3, 1:] = 99  # padding row: must be dropped, not scattered
    got = update_page_rows(dev, jnp.asarray(packed), n_slots=B)
    np.testing.assert_array_equal(np.asarray(got), host)


def test_packed_chunk_prefill_matches_legacy(tiny_params):
    """The fused-control chunk prefill is the SAME traced math as the
    3-transfer legacy entry point — logits and pool contents must agree
    bitwise (both slice the identical scalars; only the staging
    changed)."""
    from areal_tpu.engine.paged import (
        paged_chunk_prefill, paged_chunk_prefill_packed,
    )

    C, P, pg, L = 8, 4, 8, CFG.n_layers
    Hkv, hd = CFG.n_kv_heads, CFG.head_dim
    rng = np.random.RandomState(1)
    toks = rng.randint(0, CFG.vocab_size, size=C).astype(np.int32)
    valid = 5
    start = 0
    page_row = jnp.asarray([1, 2, 3, 0], jnp.int32)

    def pools():
        shape = (L, Hkv, P + 1, pg, hd)
        return (jnp.zeros(shape, jnp.float32), jnp.zeros(shape, jnp.float32))

    k1, v1 = pools()
    last1, k1, v1 = paged_chunk_prefill(
        tiny_params, CFG, jnp.asarray(toks), k1, v1, page_row,
        jnp.asarray(start, jnp.int32), jnp.asarray(valid, jnp.int32),
    )
    ctl = np.concatenate([toks, [start, valid]]).astype(np.int32)
    k2, v2 = pools()
    last2, k2, v2 = paged_chunk_prefill_packed(
        tiny_params, CFG, jnp.asarray(ctl), k2, v2, page_row,
    )
    np.testing.assert_array_equal(np.asarray(last1), np.asarray(last2))
    np.testing.assert_array_equal(np.asarray(k1), np.asarray(k2))
    np.testing.assert_array_equal(np.asarray(v1), np.asarray(v2))


def test_metrics_surface_h2d_counters(tiny_params):
    """metrics() must expose the staging telemetry the A/B reads."""
    eng = _engine(tiny_params, True)
    try:
        _run(eng, _prompts(2), max_new=8)
        m = eng.metrics()
        assert m["decode_resident"] == 1.0
        assert m["h2d_transfers_total"] > 0
        assert m["h2d_bytes_total"] > 0
        assert m["decode_blocks_total"] > 0
        assert m["h2d_per_decode_block"] == pytest.approx(
            m["h2d_transfers_total"] / m["decode_blocks_total"]
        )
    finally:
        eng.stop()

"""Worker lifecycle: configure -> poll loop -> exit, with a control server.

Counterpart of the reference's worker base (realhf/system/worker_base.py:
Worker:474, WorkerServer:71, WorkerServerStatus:36). A worker is a
process-long poll loop; a controller reaches it through a small ZMQ REP
command socket registered in name_resolve, and the worker mirrors its
status there for discovery. AsyncWorker runs the same lifecycle around an
asyncio `_poll_async`.
"""

from __future__ import annotations

import dataclasses
import enum
import json
import os
import queue
import threading
import time
from typing import Any, Dict, Optional

import zmq

from areal_tpu.base import health, logging, name_resolve, names, network, tracing
from areal_tpu.base.fault_injection import faults

logger = logging.getLogger("worker")


class WorkerServerStatus(str, enum.Enum):
    READY = "READY"
    RUNNING = "RUNNING"
    PAUSED = "PAUSED"
    COMPLETED = "COMPLETED"
    ERROR = "ERROR"
    EXITING = "EXITING"


@dataclasses.dataclass
class PollResult:
    sample_count: int = 0
    batch_count: int = 0


class WorkerServer:
    """ZMQ REP command socket + status mirror in name_resolve.

    Commands (JSON): {"cmd": "configure"|"start"|"pause"|"exit"|"status",
    "args": {...}}. Replies: {"ok": bool, "result": ...}.
    """

    def __init__(self, experiment_name: str, trial_name: str, worker_name: str):
        self.experiment_name = experiment_name
        self.trial_name = trial_name
        self.worker_name = worker_name
        self._ctx = zmq.Context.instance()
        self._sock = self._ctx.socket(zmq.REP)
        self._sock.setsockopt(zmq.LINGER, 0)
        host_ip = network.gethostip()
        port = self._sock.bind_to_random_port(f"tcp://{host_ip}")
        self.address = f"{host_ip}:{port}"
        name_resolve.add(
            names.worker(experiment_name, trial_name, worker_name),
            self.address,
            keepalive_ttl=120,
            replace=True,
        )
        self.set_status(WorkerServerStatus.READY)
        self._commands: "queue.Queue[Dict]" = queue.Queue()
        self._replies: "queue.Queue[Dict]" = queue.Queue()
        self._cmd_seq = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def set_status(self, status: WorkerServerStatus):
        name_resolve.add(
            names.worker_status(self.experiment_name, self.trial_name, self.worker_name),
            status.value,
            keepalive_ttl=240,
            replace=True,
        )

    def _serve(self):
        while not self._stop.is_set():
            if not self._sock.poll(100):
                continue
            try:
                msg = json.loads(self._sock.recv_string())
            except Exception as e:  # malformed command
                self._sock.send_string(json.dumps({"ok": False, "result": str(e)}))
                continue
            self._cmd_seq += 1
            msg["_seq"] = self._cmd_seq
            self._commands.put(msg)
            # Replies are tagged with the command's sequence number so a
            # late reply to a timed-out command can't be mistaken for the
            # answer to the next one.
            reply = None
            deadline = time.monotonic() + 300
            while time.monotonic() < deadline and not self._stop.is_set():
                try:
                    r = self._replies.get(timeout=1)
                except queue.Empty:
                    continue
                if r.get("_seq") == self._cmd_seq:
                    reply = r
                    break
                # stale reply from an earlier timed-out command: discard
            if reply is None:
                reply = {"ok": False, "result": "worker did not handle command"}
            reply.pop("_seq", None)
            self._sock.send_string(json.dumps(reply))

    def try_receive_command(self) -> Optional[Dict]:
        try:
            cmd = self._commands.get_nowait()
        except queue.Empty:
            return None
        self._pending_seq = cmd.get("_seq")
        return cmd

    def post_reply(self, ok: bool, result: Any = None):
        self._replies.put(
            {"ok": ok, "result": result, "_seq": getattr(self, "_pending_seq", None)}
        )

    def close(self):
        self._stop.set()
        self._thread.join(timeout=2)
        self._sock.close()


class WorkerControl:
    """Controller-side client for one worker's command socket."""

    def __init__(self, experiment_name: str, trial_name: str, worker_name: str,
                 timeout: float = 300.0):
        self._addr = name_resolve.wait(
            names.worker(experiment_name, trial_name, worker_name), timeout=timeout
        )
        self._ctx = zmq.Context.instance()
        self._sock = self._make_socket()

    def _make_socket(self) -> zmq.Socket:
        sock = self._ctx.socket(zmq.REQ)
        sock.setsockopt(zmq.LINGER, 0)
        sock.connect(f"tcp://{self._addr}")
        return sock

    def command(self, cmd: str, timeout_ms: int = 300_000, **args) -> Any:
        self._sock.send_string(json.dumps({"cmd": cmd, "args": args}))
        if not self._sock.poll(timeout_ms):
            # A REQ socket that never got its reply is stuck in the
            # awaiting-reply state; recreate it so the client stays usable.
            self._sock.close()
            self._sock = self._make_socket()
            raise TimeoutError(f"command {cmd!r} timed out")
        reply = json.loads(self._sock.recv_string())
        if not reply.get("ok"):
            raise RuntimeError(f"command {cmd!r} failed: {reply.get('result')}")
        return reply.get("result")

    def close(self):
        self._sock.close()


def worker_status(experiment_name: str, trial_name: str, worker_name: str) -> Optional[WorkerServerStatus]:
    try:
        v = name_resolve.get(names.worker_status(experiment_name, trial_name, worker_name))
        return WorkerServerStatus(v)
    except name_resolve.NameEntryNotFoundError:
        return None


class Worker:
    """Synchronous worker: subclass `_configure` and `_poll`."""

    def __init__(self, server: Optional[WorkerServer] = None):
        self._server = server
        self._configured = False
        self._running = False
        self._exiting = False
        self.config: Any = None
        self.experiment_name = ""
        self.trial_name = ""
        self.worker_name = ""

    # -- subclass API ---------------------------------------------------
    def _configure(self, config) -> None:
        raise NotImplementedError()

    def _poll(self) -> PollResult:
        raise NotImplementedError()

    def _exit_hook(self):
        pass

    # -- lifecycle ------------------------------------------------------
    def configure(self, config, experiment_name: str = "", trial_name: str = "",
                  worker_name: str = ""):
        self.config = config
        self.experiment_name = experiment_name or getattr(config, "experiment_name", "")
        self.trial_name = trial_name or getattr(config, "trial_name", "")
        self.worker_name = worker_name or getattr(config, "worker_name", "")
        if self.worker_name:
            # Scope env-armed chaos faults (AREAL_FAULTS "@worker" specs)
            # to this worker before any injection point can be hit.
            faults.set_scope(self.worker_name)
            # Label this process's RL-trace shard and scope the default
            # shard dir per experiment/trial (no-op unless
            # AREAL_RL_TRACE=1).
            tracing.configure_worker(
                self.worker_name, self.experiment_name, self.trial_name
            )
        self._configure(config)
        self._configured = True
        self._running = True
        if self.experiment_name and self.trial_name and self.worker_name:
            # Fault-domain lease: beaten from the poll loop, so a hung
            # worker (not just a dead one) goes stale and the watchdog /
            # gserver manager can isolate it.
            try:
                self._heartbeat = health.Heartbeat(
                    self.experiment_name,
                    self.trial_name,
                    self.worker_name,
                    payload=self._heartbeat_payload(),
                    ttl=self._heartbeat_ttl(),
                )
            except Exception:
                logger.warning("heartbeat registration failed", exc_info=True)
        if self._server:
            self._server.set_status(WorkerServerStatus.RUNNING)

    def _heartbeat_payload(self) -> Dict[str, Any]:
        """Extra fields for this worker's health record (subclasses add
        e.g. their HTTP address so consumers can map member -> endpoint)."""
        return {"pid": os.getpid()}

    def _heartbeat_ttl(self) -> Optional[float]:
        """Per-role TTL override (None = default_ttl / AREAL_HEALTH_TTL).
        Roles whose poll loop can legitimately block for long stretches
        return a TTL covering that stretch, so the supervisor's stale-
        heartbeat hang detection doesn't fire on healthy blocking."""
        return None

    def _beat(self):
        hb = getattr(self, "_heartbeat", None)
        if hb is not None:
            hb.beat()

    def _stop_heartbeat(self):
        hb = getattr(self, "_heartbeat", None)
        if hb is not None:
            hb.stop()

    def _handle_commands(self):
        if not self._server:
            return
        msg = self._server.try_receive_command()
        if msg is None:
            return
        cmd = msg.get("cmd")
        try:
            if cmd == "pause":
                self._running = False
                self._server.set_status(WorkerServerStatus.PAUSED)
                self._server.post_reply(True)
            elif cmd == "start":
                self._running = True
                self._server.set_status(WorkerServerStatus.RUNNING)
                self._server.post_reply(True)
            elif cmd == "exit":
                self._exiting = True
                self._server.post_reply(True)
            elif cmd == "status":
                self._server.post_reply(True, "RUNNING" if self._running else "PAUSED")
            else:
                self._server.post_reply(False, f"unknown command {cmd!r}")
        except Exception as e:
            self._server.post_reply(False, str(e))

    def run(self):
        """Poll until completion or exit command."""
        assert self._configured, "configure() before run()"
        logger.info("worker %s starts running", self.worker_name)
        try:
            while not self._exiting:
                self._handle_commands()
                self._beat()
                faults.maybe_fail("worker.poll")
                if not self._running:
                    time.sleep(0.05)
                    continue
                r = self._poll()
                if r is None:
                    # Subclass signalled completion.
                    break
                if r.batch_count == 0:
                    time.sleep(0.002)
            if self._server:
                self._server.set_status(WorkerServerStatus.COMPLETED)
        except Exception:
            if self._server:
                self._server.set_status(WorkerServerStatus.ERROR)
            raise
        finally:
            self._stop_heartbeat()
            self._exit_hook()
            tracing.flush()

    def exit(self):
        self._exiting = True


class AsyncWorker(Worker):
    """Worker whose poll is an async coroutine (`_poll_async`)."""

    async def _poll_async(self) -> PollResult:
        raise NotImplementedError()

    def run(self):
        import asyncio

        assert self._configured, "configure() before run()"

        async def _loop():
            while not self._exiting:
                self._handle_commands()
                self._beat()
                await faults.maybe_fail_async("worker.poll")
                if not self._running:
                    await asyncio.sleep(0.05)
                    continue
                r = await self._poll_async()
                if r is None:
                    break
                if r.batch_count == 0:
                    await asyncio.sleep(0.002)

        try:
            asyncio.run(_loop())
            if self._server:
                self._server.set_status(WorkerServerStatus.COMPLETED)
        except Exception:
            if self._server:
                self._server.set_status(WorkerServerStatus.ERROR)
            raise
        finally:
            self._stop_heartbeat()
            self._exit_hook()
            tracing.flush()

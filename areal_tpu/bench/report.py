"""Report assembly: a BENCH_rNN-style JSON from the bank.

The report (``areal-bench-report/v1``) has three evidence strata, kept
apart by construction:

- ``headline``     driver-verified claims (train TFLOP/s, gen tok/s).
                   A headline entry whose backing record is NOT
                   driver-verified is stamped ``"evidence": "proxy"``
                   and forces top-level ``driver_verified: false`` —
                   CPU smoke numbers can flow through the same pipe
                   but can never masquerade as chip results.
- ``phases``       the full banked records (measure + compile), each
                   with its attestation block.
- ``proxy``        CPU/virtual-mesh evidence: proxy phase records plus
                   the 8-device dryrun passthrough from the newest
                   MULTICHIP json, all labeled non-driver-verified.

The top-level ``metric/value/unit/vs_baseline`` keys keep the driver
contract the previous rounds' BENCH artifacts parsed.
"""

from __future__ import annotations

import glob
import json
import os
import sys
import time
from typing import Dict, Optional

from areal_tpu.base import env_registry
from areal_tpu.bench import bank, phases
from areal_tpu.bench._util import repo_root
from areal_tpu.bench.workloads import BASELINE_TFLOPS

HEADLINE_KEYS = {
    # phase -> (value key inside the record, report key)
    "train_tflops": ("train_tflops", "train_tflops_per_chip"),
    "gen_tps": ("gen_tps", "gen_tokens_per_sec_per_chip"),
    "gen_long_tps": ("gen_long_tps", "gen_long_tokens_per_sec_per_chip"),
    "serving_http": ("serving_http_tps", "serving_http_tokens_per_sec"),
}


def find_latest_multichip(repo_root_override: Optional[str] = None) -> Optional[str]:
    root = repo_root_override or repo_root()
    paths = sorted(glob.glob(os.path.join(root, "MULTICHIP_r*.json")))
    return paths[-1] if paths else None


def build_report(
    bank_path: Optional[str] = None,
    multichip_path: Optional[str] = None,
    round_tag: Optional[str] = None,
) -> Dict:
    # Freshness gate mirrors is_banked's resume TTL: an ok record left
    # over from an old interrupted round must never be published as this
    # round's evidence (it becomes a missing phase -> partial instead).
    max_age_s = env_registry.get_float("AREAL_BENCH_STATE_TTL_S")
    records = bank.load_bank(bank_path, max_age_s=max_age_s)
    measures = {p: r for (p, ps), r in records.items() if ps == "measure"}
    compiles = {p: r for (p, ps), r in records.items() if ps == "compile"}

    report: Dict = {
        "schema": bank.REPORT_SCHEMA,
        "generated_at": time.time(),
        "metric": "train_tflops_per_chip",
        "value": 0.0,
        "unit": "TFLOP/s",
        "vs_baseline": 0.0,
        "driver_verified": False,
        "partial": False,
        "headline": {},
        "phases": {},
        "compiled": {},
        "proxy": {},
        "errors": {},
    }
    if round_tag:
        report["round"] = round_tag

    spec_by_name = {s.name: s for s in phases.all_phases()}
    for name, rec in measures.items():
        spec = spec_by_name.get(name)
        proxy = bool(spec.proxy) if spec is not None else (
            not rec["attestation"].get("driver_verified", False)
        )
        section = "proxy" if proxy else "phases"
        report[section][name] = rec
        if rec["status"] != "ok":
            report["errors"][name] = rec.get("error")
            continue
        if proxy or name not in HEADLINE_KEYS:
            continue
        value_key, report_key = HEADLINE_KEYS[name]
        if value_key not in rec["value"]:
            continue
        dv = bool(rec["attestation"].get("driver_verified", False))
        entry = {
            "value": round(float(rec["value"][value_key]), 2),
            "driver_verified": dv,
        }
        if not dv:
            entry["evidence"] = "proxy"
        report["headline"][report_key] = entry
    for name, rec in compiles.items():
        report["compiled"][name] = rec

    # Driver-contract top-level keys from the train record.
    train = report["headline"].get("train_tflops_per_chip")
    if train is not None:
        report["value"] = train["value"]
        report["vs_baseline"] = round(train["value"] / BASELINE_TFLOPS, 3)
        report["driver_verified"] = train["driver_verified"]
    tr = measures.get("train_tflops")
    if tr is not None and tr["status"] == "ok":
        for k, v in (tr["value"].get("overlap") or {}).items():
            report[f"train_{k}"] = round(float(v), 4)
        # Per-chip derivation stays consistent with the phase: the
        # banked train_tflops IS per-chip; surface the mesh it ran on.
        if tr["value"].get("n_devices") is not None:
            report["train_n_devices"] = int(tr["value"]["n_devices"])
        if isinstance(tr["value"].get("mesh_shape"), dict):
            report["train_mesh_shape"] = tr["value"]["mesh_shape"]

    # 1->N scaling curve summary (full points stay in the record): the
    # top-level block scaling dashboards read without opening records.
    sc = measures.get("train_tflops_scaling")
    if sc is not None and sc["status"] == "ok":
        pts = sc["value"].get("points") or []
        if pts:
            report["train_scaling"] = {
                "n_devices_max": pts[-1].get("n_devices"),
                "per_chip_at_1": pts[0].get("train_tflops_per_chip"),
                "per_chip_at_max": pts[-1].get("train_tflops_per_chip"),
                "scaling_efficiency": round(
                    float(sc["value"].get("scaling_efficiency", 0.0)), 4
                ),
                "driver_verified": bool(
                    sc["attestation"].get("driver_verified", False)
                ),
            }

    # Default driver phases that never banked an ok measure -> partial.
    for spec in phases.default_phases():
        if spec.proxy:
            continue
        rec = measures.get(spec.name)
        if rec is None or rec["status"] != "ok":
            report["partial"] = True

    # p99-TTFT SLO gating (docs/serving.md): any serving record stamped
    # ttft_slo_violated surfaces at the report top level — a violating
    # record can flow through the pipe but never silently, and the
    # one-line driver contract carries the flag too (result_line).
    slo_violations = {}
    for section in ("phases", "proxy"):
        for name, rec in report[section].items():
            val = (rec or {}).get("value") or {}
            if val.get("ttft_slo_violated"):
                slo_violations[name] = {
                    "ttft_slo_ms": val.get("ttft_slo_ms"),
                    "headline_ttft_p99_ms": val.get(
                        "headline_ttft_p99_ms",
                        val.get("disagg_ttft_p99_ms"),
                    ),
                }
    if slo_violations:
        report["slo_violations"] = slo_violations

    rl = collect_rl_trace()
    if rl is not None:
        report["rl_trace"] = rl

    mc = multichip_path or find_latest_multichip()
    if mc and os.path.exists(mc):
        try:
            with open(mc) as f:
                payload = json.load(f)
            report["proxy"]["multichip_dryrun"] = {
                "source": os.path.basename(mc),
                "driver_verified": False,
                "evidence": "proxy",
                "result": payload,
            }
        except (OSError, ValueError) as e:
            report["errors"]["multichip_dryrun"] = repr(e)
    return report


def collect_rl_trace() -> Optional[Dict]:
    """With AREAL_RL_TRACE=1, fold the RL-trace verdict (overlap score,
    rollout latency, staleness) into the report — shards come from
    whatever traced run wrote AREAL_RL_TRACE_DIR (e.g. an async e2e
    launched alongside the bench). See docs/observability.md."""
    from areal_tpu.base import tracing

    if not tracing.enabled():
        return None
    try:
        from areal_tpu.utils import rl_trace

        return rl_trace.summarize(tracing.trace_dir())
    except Exception as e:
        print(f"bench: rl_trace summary unavailable ({e!r})",
              file=sys.stderr, flush=True)
        return None


def result_line(report: Dict) -> Dict:
    """The one-line JSON the bench driver parses — same keys the old
    monolithic bench printed, derived from the report."""
    out = {
        "metric": report["metric"],
        "value": report["value"],
        "unit": report["unit"],
        "vs_baseline": report["vs_baseline"],
        "driver_verified": report["driver_verified"],
    }
    for key in ("gen_tokens_per_sec_per_chip",
                "gen_long_tokens_per_sec_per_chip"):
        entry = report["headline"].get(key)
        if entry is not None:
            out[key] = round(float(entry["value"]), 1)
    for k, v in report.items():
        if k.startswith("train_") and k != "train_tflops_per_chip":
            out[k] = v
    rl = report.get("rl_trace") or {}
    for k in ("overlap_score", "rollout_e2e_p50_ms", "rollout_e2e_p95_ms",
              "reprefill_tokens"):
        if k in rl:
            out[f"rl_{k}"] = round(float(rl[k]), 4)
    if rl.get("staleness_hist"):
        out["rl_staleness_hist"] = rl["staleness_hist"]
    if report.get("slo_violations"):
        # SLO breaches ride the one-line contract: the driver (and any
        # human skimming the round) sees the stamp without opening the
        # full report.
        out["slo_violations"] = sorted(report["slo_violations"])
    if report.get("partial"):
        out["partial"] = True
        # "error" on the one-line contract means the ROUND is impaired
        # (old bench: deadline/abort only). A lingering non-default
        # failure or a corrupt MULTICHIP passthrough stays visible in
        # the full report's errors section without flagging a clean run.
        if report.get("errors"):
            out["error"] = "; ".join(
                f"{k}: {str(v)[:120]}" for k, v in report["errors"].items()
            )
    return out


def write_report(report: Dict, path: str) -> str:
    import threading

    # pid AND thread id: the global-deadline timer thread may flush
    # concurrently with the main thread in the same process — two
    # writers on one tmp file would os.replace() interleaved JSON over
    # the round's artifact.
    tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
    with open(tmp, "w") as f:
        json.dump(report, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path

"""Mixture-of-experts layer: top-k router + capacity-based dispatch.

Counterpart of the reference's MoE modules (realhf/impl/model/modules/moe/
router.py:242, token_dispatcher.py, experts.py) rebuilt TPU-first: instead
of the reference's permute/unpermute token dispatcher + grouped GEMM, the
classic GShard/Switch einsum formulation — dispatch/combine tensors of
shape [T, E, C] contracted against stacked expert weights [E, D, F] — so
the whole layer is three large einsums that XLA tiles onto the MXU, and
expert parallelism falls out of sharding E over the `fsdp` mesh axis
(parallel/sharding.py: dispatch contracts token-sharded activations
against expert-sharded weights, so GSPMD inserts the token all-to-all —
the reference has no EP at all).

Load-balance aux loss and router z-loss follow the Switch/ST-MoE
formulas (reference router.py aux_loss/z_loss). Tokens beyond an
expert's capacity are dropped (contribute zero), standard for the
einsum formulation; capacity_factor controls the drop rate, and the
realized drop rate is returned in the aux dict (surfaced in train stats
as moe_drop_rate).

The alternative `dispatch="dropless"` path matches the reference
dispatcher's zero-drop guarantee (token_dispatcher.py) the TPU way:
tokens sort by expert id and the expert FFN runs as `lax.ragged_dot`
grouped matmuls with per-expert group sizes — static shapes, no
capacity buffer, exact at any router skew. Tradeoff: the grouped
matmul does not yet shard over the expert axis (no EP), so capacity
dispatch remains the default for expert-parallel runs.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from areal_tpu.models.config import TransformerConfig


def moe_mlp(
    x: jnp.ndarray,  # [..., D]
    mp: Dict[str, Any],  # router [D, E], w_gate/w_up [E, D, F], w_down [E, F, D]
    cfg: TransformerConfig,
    cdt,
    capacity_factor: float = None,
    token_mask: jnp.ndarray = None,  # [...] bool, True = real token
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Returns (y with x's shape, {"load_balance_loss", "z_loss",
    "drop_rate"}). token_mask marks real (non-padding) tokens: the
    reported drop_rate then counts only real routings — padding rows
    route too (static shapes) and would otherwise dilute the rate."""
    moe = cfg.moe
    if capacity_factor is None:
        capacity_factor = moe.capacity_factor
    E, k = moe.num_experts, moe.top_k
    lead_shape = x.shape[:-1]
    D = x.shape[-1]
    xt = x.reshape(-1, D)
    T = xt.shape[0]

    # Router in fp32 for stable softmax (reference router.py casts too).
    logits = (xt.astype(jnp.float32) @ mp["router"].astype(jnp.float32))  # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)

    # top-k expert choice per token.
    top_p, top_e = jax.lax.top_k(probs, k)  # [T, k]
    if moe.routed_scaling_factor != 1.0:
        top_p = top_p * moe.routed_scaling_factor
    # renormalize the selected gates (mixtral convention)
    top_p = top_p / jnp.maximum(top_p.sum(-1, keepdims=True), 1e-9)

    choice_e = top_e.T.reshape(-1)  # [k*T] expert ids, choice-major
    gate = top_p.T.reshape(-1)  # [kT], aligned with choice_e
    tok_idx = jnp.tile(jnp.arange(T), k)
    act = jax.nn.silu if cfg.activation == "silu" else jax.nn.gelu

    if moe.dispatch == "dropless":
        # Sort (token, choice) pairs by expert; the expert FFN becomes
        # ragged grouped matmuls with per-expert group sizes. Static
        # shapes (kT rows regardless of skew), zero drops.
        order = jnp.argsort(choice_e)  # stable: keeps priority order
        group_sizes = jnp.bincount(choice_e, length=E)
        xs = xt[tok_idx[order]].astype(cdt)  # [kT, D] sorted by expert
        wg = mp["w_gate"].astype(cdt)
        wu = mp["w_up"].astype(cdt)
        wd = mp["w_down"].astype(cdt)
        h = act(jax.lax.ragged_dot(xs, wg, group_sizes))
        h = h * jax.lax.ragged_dot(xs, wu, group_sizes)
        ys = jax.lax.ragged_dot(h, wd, group_sizes)  # [kT, D]
        y = (
            jnp.zeros((T, D), cdt)
            .at[tok_idx[order]]
            .add(gate[order].astype(cdt)[:, None] * ys)
        )
        drop_rate = jnp.zeros((), jnp.float32)
    else:
        C = max(1, int(capacity_factor * T * k / E))
        # Position of each (token, choice) within its expert's capacity
        # buffer: one-hot over experts -> exclusive cumsum over the
        # flattened (k, T) priority order (choice 0 of every token
        # first).
        onehot = jax.nn.one_hot(choice_e, E, dtype=jnp.int32)  # [kT, E]
        pos_in_e = jnp.cumsum(onehot, axis=0) - onehot  # exclusive
        pos = jnp.sum(pos_in_e * onehot, axis=-1)  # [kT]
        keep = pos < C

        # dispatch [T, E, C] / combine [T, E, C]
        disp = jnp.zeros((T, E, C), bool)
        disp = disp.at[tok_idx, choice_e, jnp.minimum(pos, C - 1)].max(keep)
        comb = jnp.zeros((T, E, C), jnp.float32)
        comb = comb.at[tok_idx, choice_e, jnp.minimum(pos, C - 1)].add(
            jnp.where(keep, gate, 0.0)
        )

        xe = jnp.einsum("tec,td->ecd", disp.astype(cdt), xt.astype(cdt))  # [E, C, D]
        h = act(jnp.einsum("ecd,edf->ecf", xe, mp["w_gate"].astype(cdt)))
        h = h * jnp.einsum("ecd,edf->ecf", xe, mp["w_up"].astype(cdt))
        ye = jnp.einsum("ecf,efd->ecd", h, mp["w_down"].astype(cdt))  # [E, C, D]
        y = jnp.einsum("tec,ecd->td", comb.astype(cdt), ye)  # [T, D]
        # Realized drop rate: fraction of REAL (token, choice) routings
        # that exceeded their expert's capacity this step. The quality
        # risk of the einsum formulation under router skew — surfaced in
        # train stats so it is measured, not assumed.
        if token_mask is not None:
            mask_k = jnp.tile(token_mask.reshape(-1), k)  # aligns choice_e
            real = jnp.sum(mask_k.astype(jnp.float32))
            dropped = jnp.sum((~keep & mask_k).astype(jnp.float32))
            drop_rate = dropped / jnp.maximum(real, 1.0)
        else:
            # Clamp: XLA's mean (sum * approx-reciprocal) can round an
            # exact 1.0 to 1.0000000419, making this ~-4e-8.
            drop_rate = jnp.maximum(
                1.0 - jnp.mean(keep.astype(jnp.float32)), 0.0
            )

    # Switch load-balance loss: E * sum_e f_e * P_e, where f_e is the
    # fraction of (token, choice) routings to e and P_e the mean prob.
    f_e = jnp.mean(jax.nn.one_hot(top_e, E, dtype=jnp.float32), axis=(0, 1))
    P_e = jnp.mean(probs, axis=0)
    load_balance = E * jnp.sum(f_e * P_e)
    z = jnp.mean(jax.scipy.special.logsumexp(logits, axis=-1) ** 2)

    return y.reshape(*lead_shape, D), {
        "load_balance_loss": load_balance,
        "z_loss": z,
        "drop_rate": drop_rate,
    }


def init_moe_params(cfg: TransformerConfig, dense_fn, keys) -> Dict[str, Any]:
    """Stacked per-layer MoE params (L leading dim, matching the scan)."""
    moe = cfg.moe
    L, D, E = cfg.n_layers, cfg.hidden_dim, moe.num_experts
    F = moe.expert_intermediate_dim or cfg.intermediate_dim
    return {
        "router": dense_fn(keys[0], (L, D, E)),
        "w_gate": dense_fn(keys[1], (L, E, D, F)),
        "w_up": dense_fn(keys[2], (L, E, D, F)),
        "w_down": dense_fn(keys[3], (L, E, F, D)),
    }

"""ISSUE 6 multi-server acceptance, all real components in-process:
2 GenerationServer workers (real ServingEngines on CPU jax, bounded
admission queues) behind a real GserverManager, driven by the real
PartialRolloutManager client.

Asserted end to end:
- affinity routing sends a session's follow-up chunk to the
  prefix-holding server, measured via per-server prefix_cache_hit_rate
  (/metrics: hits on exactly one server);
- when the affinity target load-sheds with 429 (admission watermark),
  the client backs off with the Retry-After hint and the manager SPILLS
  the session to the other server — the shed server stays healthy
  (deliberate backpressure is not a failure);
- every routing decision is visible in the PR 3 trace
  (manager.schedule spans with policy=affinity / spill), alongside the
  server-side load_shed marker;
- fleet TTFT/ITL percentiles aggregate into the manager /status next to
  prefix_cache."""

import asyncio
import json
import threading
import time
import urllib.request
import uuid

import pytest

from areal_tpu.api.config import ModelAbstraction
from areal_tpu.api.model_api import GenerationHyperparameters
from areal_tpu.api.system_api import (
    GenerationServerConfig,
    GserverManagerConfig,
)
from tests import fixtures

pytestmark = pytest.mark.serial

# tests/engine/test_prefix_cache.small_cfg as a factory dict; the engine
# geometry below (B=4, page 16, block 4, bucket 16, max_seq 256) matches
# that module's engines, so an in-process tier-1 run reuses the
# already-compiled serving programs. Prefix parking needs sequences
# >= page_size tokens, hence the 20-token prompt below.
MODEL_CFG = dict(
    n_layers=2, hidden_dim=64, n_q_heads=4, n_kv_heads=2, head_dim=16,
    intermediate_dim=128, vocab_size=256, max_position_embeddings=512,
    compute_dtype="float32",
)
PROMPT = list(range(20, 40))


def _metrics(url):
    text = urllib.request.urlopen(url + "/metrics", timeout=30).read().decode()
    out = {}
    for line in text.splitlines():
        parts = line.split()
        if len(parts) == 2:
            try:
                out[parts[0]] = float(parts[1])
            except ValueError:
                out[parts[0]] = parts[1]
    return out


def _wait_until(cond, timeout, msg):
    deadline = time.monotonic() + fixtures.scale_timeout(timeout)
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.mark.timeout(600)
def test_affinity_routing_spill_on_429_and_trace(tmp_path, monkeypatch):
    from areal_tpu.base import name_resolve, names, tracing
    from areal_tpu.engine.serving import GenRequest
    from areal_tpu.system.generation_server import GenerationServer
    from areal_tpu.system.gserver_manager import GserverManager
    from areal_tpu.system.partial_rollout import PartialRolloutManager
    from areal_tpu.utils import rl_trace

    exp, trial = f"affinity-{uuid.uuid4().hex[:6]}", "t0"
    trace_dir = str(tmp_path / "rl_trace")
    monkeypatch.setenv("AREAL_HEALTH_TTL", "120")
    monkeypatch.setenv("AREAL_RL_TRACE", "1")
    monkeypatch.setenv("AREAL_RL_TRACE_DIR", trace_dir)
    tracing.reconfigure()
    name_resolve.reconfigure("nfs", record_root=str(tmp_path / "nr"))

    servers = []
    mgr = None
    mgr_thread = None
    prm = None
    loop = asyncio.new_event_loop()
    try:
        for i in range(2):
            cfg = GenerationServerConfig(
                experiment_name=exp, trial_name=trial, server_index=i,
                model=ModelAbstraction(
                    "tpu_transformer", args=dict(config=dict(MODEL_CFG))
                ),
                max_concurrent_requests=4, max_seq_len=256,
                kv_page_size=16, decode_block_steps=4, prompt_bucket=16,
                prefix_cache_tokens=2048,
                # Bounded admission queue: one backlogged request is
                # already over the watermark -> 429 + Retry-After.
                max_queue_depth=1, shed_retry_after_s=0.2,
                seed=i,
            )
            w = GenerationServer()
            w.configure(cfg, experiment_name=exp, trial_name=trial,
                        worker_name=cfg.worker_name)
            servers.append(w)

        mgr = GserverManager()
        mgr.configure(
            GserverManagerConfig(
                experiment_name=exp, trial_name=trial, model_name="actor",
                n_servers=2, schedule_policy="least_requests",
                train_batch_size=4, max_head_offpolicyness=1000,
                health_check_interval=0.5,
            ),
            experiment_name=exp, trial_name=trial,
            worker_name="gserver_manager",
        )
        mgr_thread = threading.Thread(target=mgr.run, daemon=True)
        mgr_thread.start()
        _wait_until(lambda: len(mgr._healthy_urls()) == 2, 60,
                    "manager sees both servers")

        prm = PartialRolloutManager(
            mgr.address, new_tokens_per_chunk=4,
            request_timeout=fixtures.scale_timeout(120),
        )
        g = GenerationHyperparameters(max_new_tokens=8, greedy=True)

        # --- Phase 1: chunked session -> affinity hit on the prefix
        # holder. new_tokens_per_chunk=4 < max_new_tokens=8 forces a
        # resubmission carrying the accumulated prefix under one qid.
        out = loop.run_until_complete(
            prm._generate_one("sess/0", PROMPT, g)
        )
        assert len(out.output_ids) >= 4
        by_url = {w.address: w for w in servers}
        hits = {u: w.engine.prefix_cache_hits for u, w in by_url.items()}
        assert sorted(hits.values()) == [0, 1], hits
        aff_url = max(hits, key=hits.get)
        assert mgr._affinity.get("sess/0") == aff_url
        # Per-server hit RATE over the /metrics surface (the fleet
        # aggregation inputs): only the prefix holder has a nonzero rate.
        m_aff = _metrics(aff_url)
        assert m_aff["areal:prefix_cache_hits"] == 1.0
        assert 0.0 < (
            m_aff["areal:prefix_cache_hits"] / m_aff["areal:total_requests"]
        ) <= 1.0
        other_url = next(u for u in by_url if u != aff_url)
        assert _metrics(other_url)["areal:prefix_cache_hits"] == 0.0

        # --- Phase 2: saturate the affinity target so its admission
        # queue sheds, then continue the session: 429 -> jittered
        # backoff -> shed hint -> manager spills to the other server.
        aff_eng = by_url[aff_url].engine
        for i in range(12):
            aff_eng.submit(GenRequest(
                qid=f"blk{i}", input_ids=[9, 10, 11], max_new_tokens=200,
                greedy=True, done_cb=lambda r: None,
            ))
        _wait_until(lambda: aff_eng.queue_depth >= 1, 30,
                    "affinity target backlogged")
        other_reqs_before = by_url[other_url].engine.total_requests
        out2 = loop.run_until_complete(
            prm._generate_one("sess/0", PROMPT + out.output_ids,
                              GenerationHyperparameters(
                                  max_new_tokens=4, greedy=True))
        )
        assert len(out2.output_ids) >= 1
        assert by_url[other_url].engine.total_requests > other_reqs_before
        # Deliberate shedding never evicted the target...
        assert set(mgr._healthy_urls()) == set(by_url)
        # ...and the shed surfaced on the server's own /metrics.
        assert _metrics(aff_url)["areal:load_shed_total"] >= 1.0
        # The spill re-homed the session's affinity.
        assert mgr._affinity.get("sess/0") == other_url

        # --- Fleet latency aggregation: after a /metrics poll cycle the
        # manager /status carries merged TTFT/ITL percentiles next to
        # prefix_cache.
        def status():
            with urllib.request.urlopen(
                mgr.address + "/status", timeout=30
            ) as r:
                return json.loads(r.read())

        _wait_until(
            lambda: status()["serving_latency"]["ttft_count"] > 0, 30,
            "fleet latency aggregation",
        )
        st = status()
        assert st["serving_latency"]["ttft_p99_ms"] >= (
            st["serving_latency"]["ttft_p50_ms"]
        ) > 0
        assert st["serving_latency"]["itl_count"] > 0
        assert st["load_shed"]["total"] >= 1.0
        assert st["prefix_cache"]["prefix_cache_hits"] >= 1.0
        assert st["affinity_entries"] >= 1

        # --- PR 3 trace: the routing decisions are spans with a policy
        # attribute; the server-side shed left its own marker.
        tracing.flush()
        shards = rl_trace.load_shards(trace_dir)
        sched = [
            sp for s in shards for sp in s.spans
            if sp["name"] == "manager.schedule"
        ]
        policies = [sp.get("attrs", {}).get("policy") for sp in sched]
        assert "affinity" in policies, policies
        assert "spill" in policies, policies
        spill_span = next(
            sp for sp in sched
            if sp.get("attrs", {}).get("policy") == "spill"
        )
        assert spill_span["attrs"]["server"] == other_url
        assert spill_span["attrs"]["qid"] == "sess/0"
        assert any(
            sp["name"] == "server.load_shed"
            for s in shards for sp in s.spans
        )
    finally:
        try:
            name_resolve.add(
                names.experiment_status(exp, trial), "COMPLETE",
                replace=True,
            )
        except Exception:
            pass
        if mgr_thread is not None:
            mgr_thread.join(timeout=15)
        for w in servers:
            w._exit_hook()
        if prm is not None:
            loop.run_until_complete(prm.close())
        loop.run_until_complete(asyncio.sleep(0))
        loop.close()
        tracing.reconfigure()

"""LLaMA-family HF conversion (covers llama 1/2/3, and the shared layout
used by mistral). Reference parity: realhf/api/from_hf/llama.py."""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from areal_tpu.api.model_api import register_hf_family
from areal_tpu.models.config import TransformerConfig


def _config_from_hf(hf: Dict[str, Any], is_critic: bool = False) -> TransformerConfig:
    head_dim = hf.get("head_dim") or hf["hidden_size"] // hf["num_attention_heads"]
    rope_scaling = hf.get("rope_scaling") or {}
    rope_type = rope_scaling.get("rope_type") or rope_scaling.get("type")
    if rope_type not in (None, "default", "linear", "llama3"):
        raise NotImplementedError(
            f"rope scaling type {rope_type!r} from HF config is not supported yet"
        )
    return TransformerConfig(
        n_layers=hf["num_hidden_layers"],
        hidden_dim=hf["hidden_size"],
        n_q_heads=hf["num_attention_heads"],
        n_kv_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
        head_dim=head_dim,
        intermediate_dim=hf["intermediate_size"],
        vocab_size=hf["vocab_size"],
        max_position_embeddings=hf.get("max_position_embeddings", 4096),
        activation="silu" if hf.get("hidden_act", "silu") == "silu" else "gelu",
        mlp_type="gated",
        norm_type="rms",
        norm_eps=hf.get("rms_norm_eps", 1e-6),
        rotary_base=hf.get("rope_theta", 10000.0),
        rotary_scaling=rope_scaling.get("factor"),
        rotary_scaling_type=rope_type,
        rotary_scaling_params=dict(rope_scaling) or None,
        attn_bias=bool(hf.get("attention_bias", False)),
        tied_embeddings=bool(hf.get("tie_word_embeddings", False)),
        is_critic=is_critic,
    )


def _config_to_hf(cfg: TransformerConfig) -> Dict[str, Any]:
    hf = {
        "architectures": ["LlamaForCausalLM"],
        "model_type": "llama",
        "num_hidden_layers": cfg.n_layers,
        "hidden_size": cfg.hidden_dim,
        "num_attention_heads": cfg.n_q_heads,
        "num_key_value_heads": cfg.n_kv_heads,
        "head_dim": cfg.head_dim,
        "intermediate_size": cfg.intermediate_dim,
        "vocab_size": cfg.vocab_size,
        "max_position_embeddings": cfg.max_position_embeddings,
        "hidden_act": "silu",
        "rms_norm_eps": cfg.norm_eps,
        "rope_theta": cfg.rotary_base,
        "attention_bias": cfg.attn_bias,
        "tie_word_embeddings": cfg.tied_embeddings,
        "torch_dtype": "bfloat16",
    }
    if cfg.rotary_scaling:
        hf["rope_scaling"] = {
            "factor": cfg.rotary_scaling,
            "rope_type": cfg.rotary_scaling_type or "linear",
        }
    return hf


def params_from_hf_llama_style(
    sd: Dict[str, np.ndarray],
    cfg: TransformerConfig,
    qkv_bias: bool = False,
    qk_norm: bool = False,
) -> Dict:
    """Shared llama-layout importer. HF linear weights are [out, in] and are
    transposed into the matmul-ready [in, out] layout used on TPU."""
    L = cfg.n_layers

    def t(name):
        return np.ascontiguousarray(sd[name].astype(np.float32).T)

    def w(name):
        return sd[name].astype(np.float32)

    attn: Dict[str, np.ndarray] = {
        "wq": np.stack([t(f"model.layers.{i}.self_attn.q_proj.weight") for i in range(L)]),
        "wk": np.stack([t(f"model.layers.{i}.self_attn.k_proj.weight") for i in range(L)]),
        "wv": np.stack([t(f"model.layers.{i}.self_attn.v_proj.weight") for i in range(L)]),
        "wo": np.stack([t(f"model.layers.{i}.self_attn.o_proj.weight") for i in range(L)]),
    }
    if qkv_bias:
        attn["bq"] = np.stack([w(f"model.layers.{i}.self_attn.q_proj.bias") for i in range(L)])
        attn["bk"] = np.stack([w(f"model.layers.{i}.self_attn.k_proj.bias") for i in range(L)])
        attn["bv"] = np.stack([w(f"model.layers.{i}.self_attn.v_proj.bias") for i in range(L)])
    if qk_norm:
        attn["q_norm"] = np.stack([w(f"model.layers.{i}.self_attn.q_norm.weight") for i in range(L)])
        attn["k_norm"] = np.stack([w(f"model.layers.{i}.self_attn.k_norm.weight") for i in range(L)])

    params: Dict = {
        "embedding": {"weight": w("model.embed_tokens.weight")},
        "layers": {
            "ln1": {
                "weight": np.stack(
                    [w(f"model.layers.{i}.input_layernorm.weight") for i in range(L)]
                )
            },
            "ln2": {
                "weight": np.stack(
                    [w(f"model.layers.{i}.post_attention_layernorm.weight") for i in range(L)]
                )
            },
            "attn": attn,
            "mlp": {
                "w_gate": np.stack([t(f"model.layers.{i}.mlp.gate_proj.weight") for i in range(L)]),
                "w_up": np.stack([t(f"model.layers.{i}.mlp.up_proj.weight") for i in range(L)]),
                "w_down": np.stack([t(f"model.layers.{i}.mlp.down_proj.weight") for i in range(L)]),
            },
        },
        "final_norm": {"weight": w("model.norm.weight")},
    }
    if cfg.is_critic:
        # Critic heads don't exist in HF causal-LM checkpoints; use score/
        # v_head when present, else zero-init (reference does random init).
        if "score.weight" in sd:
            params["head"] = {"weight": t("score.weight")}
        else:
            params["head"] = {"weight": np.zeros((cfg.hidden_dim, 1), np.float32)}
    elif not cfg.tied_embeddings:
        params["head"] = {"weight": t("lm_head.weight")}
    return params


def params_to_hf_llama_style(
    params: Dict,
    cfg: TransformerConfig,
    qkv_bias: bool = False,
    qk_norm: bool = False,
) -> Dict[str, np.ndarray]:
    L = cfg.n_layers
    layers = params["layers"]
    sd: Dict[str, np.ndarray] = {
        "model.embed_tokens.weight": np.asarray(params["embedding"]["weight"]),
        "model.norm.weight": np.asarray(params["final_norm"]["weight"]),
    }
    a, m = layers["attn"], layers["mlp"]
    for i in range(L):
        sd[f"model.layers.{i}.input_layernorm.weight"] = np.asarray(layers["ln1"]["weight"][i])
        sd[f"model.layers.{i}.post_attention_layernorm.weight"] = np.asarray(layers["ln2"]["weight"][i])
        sd[f"model.layers.{i}.self_attn.q_proj.weight"] = np.asarray(a["wq"][i]).T
        sd[f"model.layers.{i}.self_attn.k_proj.weight"] = np.asarray(a["wk"][i]).T
        sd[f"model.layers.{i}.self_attn.v_proj.weight"] = np.asarray(a["wv"][i]).T
        sd[f"model.layers.{i}.self_attn.o_proj.weight"] = np.asarray(a["wo"][i]).T
        if qkv_bias:
            sd[f"model.layers.{i}.self_attn.q_proj.bias"] = np.asarray(a["bq"][i])
            sd[f"model.layers.{i}.self_attn.k_proj.bias"] = np.asarray(a["bk"][i])
            sd[f"model.layers.{i}.self_attn.v_proj.bias"] = np.asarray(a["bv"][i])
        if qk_norm:
            sd[f"model.layers.{i}.self_attn.q_norm.weight"] = np.asarray(a["q_norm"][i])
            sd[f"model.layers.{i}.self_attn.k_norm.weight"] = np.asarray(a["k_norm"][i])
        sd[f"model.layers.{i}.mlp.gate_proj.weight"] = np.asarray(m["w_gate"][i]).T
        sd[f"model.layers.{i}.mlp.up_proj.weight"] = np.asarray(m["w_up"][i]).T
        sd[f"model.layers.{i}.mlp.down_proj.weight"] = np.asarray(m["w_down"][i]).T
    if cfg.is_critic:
        sd["score.weight"] = np.asarray(params["head"]["weight"]).T
    elif not cfg.tied_embeddings:
        sd["lm_head.weight"] = np.asarray(params["head"]["weight"]).T
    return sd


def _params_from_hf(sd, cfg):
    return params_from_hf_llama_style(sd, cfg, qkv_bias=cfg.attn_bias, qk_norm=False)


def _params_to_hf(params, cfg):
    return params_to_hf_llama_style(params, cfg, qkv_bias=cfg.attn_bias, qk_norm=False)


from areal_tpu.models.hf import HFFamily  # noqa: E402

register_hf_family(
    "llama",
    HFFamily(
        name="llama",
        hf_model_type="llama",
        config_from_hf=_config_from_hf,
        config_to_hf=_config_to_hf,
        params_from_hf=_params_from_hf,
        params_to_hf=_params_to_hf,
    ),
)

"""metrics-registry checker fixtures: seeded violations per rule
(undeclared emission, f-string-built name, ambiguous startswith,
unresolved constant, dead entry) plus the exempt-pattern negatives
(prose, perf_mem_stats liveness, subset-scan gating)."""

import textwrap

from areal_tpu.lint.metrics import MetricsConfig
from areal_tpu.lint.runner import LintConfig, run_lint

_CFG = MetricsConfig(
    declared={"areal:good", "areal:amb", "areal:amb_extra",
              "perf/thing", "perf/mem_bytes"},
    constants={"GOOD": "areal:good", "AMB": "areal:amb",
               "AMB_EXTRA": "areal:amb_extra", "PERF_THING": "perf/thing",
               "PERF_MEM_BYTES": "perf/mem_bytes"},
    exported={"REGISTRY", "CONSTANTS", "parse_line", "perf_mem_stats",
              "render_docs"},
    registry_rel="metrics_registry.py",
)


def _lint(tmp_path, source, *, name="mod.py", paths=None):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    cfg = LintConfig(
        root=str(tmp_path), metrics_cfg=_CFG,
        checkers={"metrics-registry"},
    )
    return run_lint(paths or [str(p)], cfg)


def test_undeclared_emission_flagged(tmp_path):
    findings = _lint(tmp_path, """
        def emit(v):
            return [f"areal:good {v}", f"areal:brand_new {v}"]
    """)
    assert len(findings) == 1
    assert "areal:brand_new" in findings[0].message


def test_undeclared_parse_key_flagged(tmp_path):
    findings = _lint(tmp_path, """
        def read(m):
            return m.get("areal:goood")
    """)
    assert len(findings) == 1
    assert "areal:goood" in findings[0].message


def test_prose_not_flagged(tmp_path):
    # A docstring MENTIONING a name mid-sentence is not a reference.
    findings = _lint(tmp_path, '''
        def f():
            """The poll reads areal:brand_new_thing from servers."""
    ''')
    assert findings == []


def test_fstring_built_name_flagged(tmp_path):
    findings = _lint(tmp_path, """
        def emit(k, v):
            return {f"perf/{k}": v}
    """)
    assert len(findings) == 1
    assert "f-string-built" in findings[0].message


def test_ambiguous_startswith_flagged(tmp_path):
    findings = _lint(tmp_path, """
        def parse(line):
            if line.startswith("areal:amb"):
                return line
    """)
    assert len(findings) == 1
    assert "ambiguous" in findings[0].message
    assert "areal:amb_extra" in findings[0].message


def test_ambiguous_incomplete_prefix_flagged(tmp_path):
    # The probe need not be a declared name itself: "areal:amb_" is a
    # trailing-underscore prefix (skipped by the undeclared-literal
    # rule as a name under construction) yet matches two declared
    # names — it reads whichever line comes first.
    cfg = MetricsConfig(
        declared={"areal:amb_extra", "areal:amb_other"},
        constants={}, exported=set(),
        registry_rel="metrics_registry.py",
    )
    p = tmp_path / "mod.py"
    p.write_text(textwrap.dedent("""
        def parse(line):
            if line.startswith("areal:amb_"):
                return line
    """))
    lint_cfg = LintConfig(root=str(tmp_path), metrics_cfg=cfg,
                          checkers={"metrics-registry"})
    findings = run_lint([str(p)], lint_cfg)
    assert len(findings) == 1
    assert "ambiguous" in findings[0].message
    assert "areal:amb_extra" in findings[0].message


def test_family_prefix_probe_clean(tmp_path):
    # startswith("areal:") is a deliberate whole-family filter,
    # declared in FAMILY_PREFIXES — not an ambiguous line parse.
    findings = _lint(tmp_path, """
        def split(lines):
            return [l for l in lines if l.startswith("areal:")]
    """)
    assert findings == []


def test_trailing_space_disambiguates(tmp_path):
    findings = _lint(tmp_path, """
        def parse(line):
            if line.startswith("areal:amb "):
                return line
    """)
    assert findings == []


def test_unresolved_constant_flagged(tmp_path):
    findings = _lint(tmp_path, """
        from areal_tpu.base import metrics_registry

        def read(m):
            return m.get(metrics_registry.GOOD), metrics_registry.TYPO
    """)
    assert len(findings) == 1
    assert "TYPO" in findings[0].message


def test_dead_entry_flagged_only_with_registry_in_scan(tmp_path):
    (tmp_path / "metrics_registry.py").write_text(
        '_m = dict\nREG = [_m("areal:good"), _m("areal:amb"),\n'
        '       _m("areal:amb_extra"), _m("perf/thing"),\n'
        '       _m("perf/mem_bytes")]\n'
    )
    (tmp_path / "user.py").write_text(textwrap.dedent("""
        from areal_tpu.base import metrics_registry

        def emit(v, mem):
            x = f"areal:good {v}"
            y = "areal:amb", "areal:amb_extra"
            return x, y, metrics_registry.perf_mem_stats(mem)
    """))
    cfg = LintConfig(root=str(tmp_path), metrics_cfg=_CFG,
                     checkers={"metrics-registry"})
    findings = run_lint([str(tmp_path)], cfg)
    # perf/thing is dead; perf/mem_bytes stays alive through the
    # perf_mem_stats call (the declared dynamic builder).
    assert len(findings) == 1
    assert "dead registry entry perf/thing" in findings[0].message

    # Subset scan (registry not covered): no dead-entry noise.
    findings = run_lint([str(tmp_path / "user.py")], cfg)
    assert findings == []

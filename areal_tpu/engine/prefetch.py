"""Bounded background prefetch pipeline for the train/forward hot path.

The step loop's host side — FFD-pack rows, pad to the shape bucket,
`jax.device_put` — runs serially before every dispatch in the eager
path, so the device idles for exactly that long each step. AReaL's
design hides one plane's latency behind another's compute (async rollout
behind training); `HostPrefetcher` applies the same overlap one level
down: a single worker thread stages micro-batch i+1 (pack + H2D) while
the device runs step i, bounded by a depth-limited queue so host memory
and in-flight transfers can never run away.

Why one thread and not a pool: results must arrive in submission order
(gradient accumulation and `reorder_output` both assume it), and the
stage is dominated by numpy packing + the H2D call, which release the
GIL — one thread already achieves full overlap against device compute.

Telemetry contract (consumed by `JaxTrainEngine` and surfaced as
`perf/h2d_wait_ms` / `perf/dispatch_gap_ms`):
- `wait_ms`: total time the consumer blocked on an empty queue — the
  pack+transfer latency NOT hidden behind compute. Eager pipelines
  pay the full stage cost here; a healthy prefetched loop shows ~0.
- `stage_ms`: total time inside `stage_fn` (the work being hidden).
- `spans`: per-item (stage_start, stage_end, consumed_at) perf_counter
  timestamps, so tests can assert overlap structurally (stage i+1
  started before item i was consumed) instead of racing wall clocks.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterable, List, Optional, Tuple


class _Done:
    """Queue sentinel: the item stream is exhausted."""


class HostPrefetcher:
    """Run `stage_fn(item)` for each element of `items` on one background
    thread, delivering results in submission order through a bounded
    queue of `depth` slots (backpressure: the stage blocks once `depth`
    results are staged but unconsumed).

    Exceptions raised by `stage_fn` (or by the `items` iterator) are
    delivered to the consumer at the position they occurred and terminate
    the pipeline; remaining items are never staged.

    Use as an iterator, or call `get()` directly. Always `close()` (or
    exhaust) — exiting a consumer loop early without closing would leave
    the worker blocked on a full queue. Iteration closes on exhaustion
    and on exception; `close()` is idempotent.
    """

    def __init__(
        self,
        items: Iterable[Any],
        stage_fn: Callable[[Any], Any],
        depth: int = 2,
        name: str = "prefetch",
    ):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self.depth = depth
        self._items = iter(items)
        self._stage = stage_fn
        self._q: "queue.Queue[Any]" = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self.wait_ms = 0.0
        self.stage_ms = 0.0
        self.n_staged = 0
        self.n_consumed = 0
        # (stage_start, stage_end, consumed_at) per item, consumption
        # order. consumed_at is filled by get().
        self.spans: List[Tuple[float, float, Optional[float]]] = []
        self._thread = threading.Thread(
            target=self._work, name=f"{name}-worker", daemon=True
        )
        self._thread.start()

    # -- worker side ---------------------------------------------------

    def _put(self, payload) -> bool:
        """Bounded put that aborts when the consumer closed early."""
        while not self._stop.is_set():
            try:
                self._q.put(payload, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _work(self):
        while not self._stop.is_set():
            try:
                item = next(self._items)
            except StopIteration:
                self._put(_Done)
                return
            except BaseException as e:  # iterator itself failed
                self._put((None, e, 0.0, 0.0))
                return
            t0 = time.perf_counter()
            try:
                res = self._stage(item)
            except BaseException as e:
                self._put((None, e, t0, time.perf_counter()))
                return
            self.stage_ms += (time.perf_counter() - t0) * 1e3
            self.n_staged += 1
            if not self._put((res, None, t0, time.perf_counter())):
                return

    # -- consumer side -------------------------------------------------

    def get(self):
        """Next staged result in order; raises StopIteration when the
        stream is exhausted, or the original exception when the stage
        (or source iterator) failed at this position."""
        t0 = time.perf_counter()
        payload = self._q.get()
        now = time.perf_counter()
        self.wait_ms += (now - t0) * 1e3
        if payload is _Done:
            self.close()
            raise StopIteration
        res, exc, s0, s1 = payload
        if exc is not None:
            self.close()
            raise exc
        self.spans.append((s0, s1, now))
        self.n_consumed += 1
        return res

    def __iter__(self):
        while True:
            try:
                yield self.get()
            except StopIteration:
                return

    def close(self):
        """Stop the worker and release its queue slot; idempotent."""
        self._stop.set()
        # Drain so a worker blocked on put() observes the stop quickly.
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5.0)

    # -- telemetry -----------------------------------------------------

    def overlap_count(self) -> int:
        """Number of items whose staging started before the PREVIOUS
        item was consumed — the structural evidence that pack/H2D of
        micro-batch i+1 overlapped step i (no wall-clock ratios, so the
        check is stable under CI load)."""
        n = 0
        for i in range(1, len(self.spans)):
            prev_consumed = self.spans[i - 1][2]
            if prev_consumed is not None and self.spans[i][0] < prev_consumed:
                n += 1
        return n

"""Rollout-buffer write-ahead log + exactly-once sample ledger.

The durable half of the training data plane. Samples accepted off the
push/pull wire journal here (append-only JSONL, batched fsync) BEFORE
the pusher is acked, so a trainer SIGKILL can never lose an in-flight
rollout: unacked samples are redelivered by the pusher, journaled ones
are replayed from the WAL at restart. `SeqLedger` is the other half of
exactly-once — a compressed permanent-membership set over the rollout
workers' minted sequence ids, persisted atomically with the recover
record so a resume filters both WAL replay and pusher redelivery
against the same cut the engine state was taken at.

Crash safety model:
- append → fsync → ack, in that order. A kill between append and fsync
  may tear the final record; replay drops the torn tail (the sample was
  never acked, so the pusher redelivers it — admission dedup makes the
  redelivery idempotent).
- compaction (checkpoint-barrier truncation) rewrites tmp+fsync+rename,
  so a kill mid-compaction leaves the previous journal intact.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from areal_tpu.base import env_registry, logging
from areal_tpu.base.fault_injection import faults
from areal_tpu.base.wire_schemas import BUFFER_WAL_V1

logger = logging.getLogger("wal")


class SeqLedger:
    """Permanent membership set over rollout sequence ids.

    Seqs are minted per pusher as ``{pusher}/{n}`` with n counting from
    0, so membership compresses to a per-pusher contiguous watermark
    plus a sparse set of out-of-order extras above it. Unlike the
    buffer's skip-once ``ignore_ids``, membership here is permanent —
    seqs are globally unique, so "seen once" means "never again".
    """

    def __init__(self):
        # pusher -> highest n with 0..n all marked (-1 = none).
        self._water: Dict[str, int] = {}
        # pusher -> marked n's above the watermark (gaps pending).
        self._extras: Dict[str, Set[int]] = {}

    @staticmethod
    def _parse(seq: str) -> Tuple[str, int]:
        pusher, _, n = seq.rpartition("/")
        return pusher, int(n)

    def mark(self, seq: str):
        pusher, n = self._parse(seq)
        water = self._water.get(pusher, -1)
        if n <= water:
            return
        extras = self._extras.setdefault(pusher, set())
        extras.add(n)
        while water + 1 in extras:
            water += 1
            extras.discard(water)
        self._water[pusher] = water
        if not extras:
            self._extras.pop(pusher, None)

    def __contains__(self, seq: str) -> bool:
        pusher, n = self._parse(seq)
        if n <= self._water.get(pusher, -1):
            return True
        return n in self._extras.get(pusher, ())

    def to_dict(self) -> Dict[str, Any]:
        """JSON/pickle-safe snapshot (RecoverInfo.consumed_seqs)."""
        return {
            "water": dict(self._water),
            "extras": {p: sorted(s) for p, s in self._extras.items()},
        }

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "SeqLedger":
        led = cls()
        if d:
            led._water = {p: int(n) for p, n in d.get("water", {}).items()}
            led._extras = {
                p: set(ns) for p, ns in d.get("extras", {}).items() if ns
            }
        return led


class RolloutWAL:
    """Append-only JSONL journal with a schema header and batched fsync.

    Layout: line 1 is ``{"schema": "areal-buffer-wal/v1"}``, every
    further line one accepted-sample record. `append()` buffers; the
    fsync (and any `on_durable` callbacks registered with appended
    records — the deferred pusher acks) lands on `maybe_sync()` once
    AREAL_WAL_FSYNC_MS elapsed, or immediately on `sync()`.
    """

    def __init__(self, path: str, fsync_ms: Optional[float] = None,
                 schema: str = BUFFER_WAL_V1):
        self.path = path
        # Which areal-*-wal/vN header this journal carries: the buffer
        # WAL by default; the gateway's usage ledger reuses the same
        # torn-tail/compaction machinery under its own schema tag.
        self.schema = schema
        if fsync_ms is None:
            fsync_ms = env_registry.get_float("AREAL_WAL_FSYNC_MS")
        self._fsync_s = max(0.0, float(fsync_ms)) / 1000.0
        self._f = None
        self._dirty = False
        self._oldest_dirty: Optional[float] = None
        self._on_durable: List[Callable[[], None]] = []
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    # -- recovery ---------------------------------------------------------

    def replay(self) -> List[Dict[str, Any]]:
        """Read back journaled records, tolerating a torn tail.

        Decodes line by line; the first undecodable line (a record torn
        by a kill mid-append) and everything after it is discarded AND
        truncated off the file, so later appends never interleave with
        torn bytes. Returns the surviving records and leaves the file
        open for append.
        """
        records: List[Dict[str, Any]] = []
        good_end = 0
        torn = False
        if os.path.exists(self.path):
            with open(self.path, "rb") as f:
                data = f.read()
            offset = 0
            first = True
            for line in data.split(b"\n"):
                end = offset + len(line) + 1  # +1 for the newline
                if end > len(data) + 1:
                    break
                # A final line without its newline is a torn append.
                terminated = end <= len(data)
                try:
                    if line:
                        rec = json.loads(line)
                    else:
                        rec = None
                except (ValueError, UnicodeDecodeError):
                    torn = True
                    break
                if not terminated and line:
                    torn = True
                    break
                if rec is not None:
                    if first:
                        if rec.get("schema") != self.schema:
                            raise ValueError(
                                f"WAL {self.path} has unsupported schema "
                                f"{rec.get('schema')!r}"
                            )
                        first = False
                    else:
                        records.append(rec)
                if line:
                    good_end = min(end, len(data))
                offset = end
            if torn or good_end < len(data):
                logger.warning(
                    "WAL %s: dropping torn tail (%d bytes past offset %d)",
                    self.path, len(data) - good_end, good_end,
                )
                with open(self.path, "r+b") as f:
                    f.truncate(good_end)
        self._open_for_append(write_header=not os.path.exists(self.path)
                              or os.path.getsize(self.path) == 0)
        return records

    # -- append path ------------------------------------------------------

    def _open_for_append(self, write_header: bool):
        self._f = open(self.path, "ab")
        if write_header:
            self._f.write(
                json.dumps({"schema": self.schema},
                           separators=(",", ":")).encode() + b"\n"
            )
            self._f.flush()
            os.fsync(self._f.fileno())

    def append(self, record: Dict[str, Any],
               on_durable: Optional[Callable[[], None]] = None):
        """Journal one record; `on_durable` fires after the fsync that
        covers it (the deferred pusher ack)."""
        faults.maybe_fail("buffer.wal_append")
        if self._f is None:
            self._open_for_append(
                write_header=not os.path.exists(self.path)
                or os.path.getsize(self.path) == 0)
        self._f.write(
            json.dumps(record, separators=(",", ":")).encode() + b"\n"
        )
        self._dirty = True
        if self._oldest_dirty is None:
            self._oldest_dirty = time.monotonic()
        if on_durable is not None:
            self._on_durable.append(on_durable)
        self.maybe_sync()

    def maybe_sync(self, force: bool = False) -> bool:
        """Batched fsync: flush once the oldest unsynced record has sat
        for AREAL_WAL_FSYNC_MS (or immediately when forced)."""
        if not self._dirty:
            return False
        if not force and self._oldest_dirty is not None:
            if time.monotonic() - self._oldest_dirty < self._fsync_s:
                return False
        self._f.flush()
        os.fsync(self._f.fileno())
        self._dirty = False
        self._oldest_dirty = None
        callbacks, self._on_durable = self._on_durable, []
        for cb in callbacks:
            try:
                cb()
            except Exception:
                logger.exception("WAL on_durable callback failed")
        return True

    def sync(self) -> bool:
        return self.maybe_sync(force=True)

    # -- checkpoint-barrier truncation ------------------------------------

    def compact(self, keep: Callable[[Dict[str, Any]], bool]) -> int:
        """Atomically rewrite the journal keeping only records where
        ``keep(record)`` — the checkpoint-barrier prefix truncation
        (records whose seqs the durable ledger marked consumed are GC'd).
        Returns the number of records dropped."""
        self.sync()
        if self._f is not None:
            self._f.close()
            self._f = None
        kept: List[bytes] = []
        dropped = 0
        if os.path.exists(self.path):
            with open(self.path, "rb") as f:
                for i, line in enumerate(f.read().split(b"\n")):
                    if not line or i == 0:
                        continue  # header rewritten below
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue  # torn tail — never carried forward
                    if keep(rec):
                        kept.append(line)
                    else:
                        dropped += 1
        tmp = self.path + f".tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(json.dumps({"schema": self.schema},
                               separators=(",", ":")).encode() + b"\n")
            for line in kept:
                f.write(line + b"\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        dir_fd = os.open(os.path.dirname(self.path) or ".", os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)
        self._open_for_append(write_header=False)
        return dropped

    def close(self):
        if self._f is not None:
            self.sync()
            self._f.close()
            self._f = None

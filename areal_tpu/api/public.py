"""OpenAI-compatible public wire schemas for the tenant gateway.

The parsing/formatting half of the multi-tenant front door
(system/gateway.py): request validation for ``POST /v1/completions``
and ``POST /v1/chat/completions``, SSE chunk/terminator framing, and
the response envelopes — every JSON body is stamped with the
``areal-gateway/v1`` schema tag (base/wire_schemas.py) so clients can
reject payloads from a different protocol generation.

Deliberately stdlib-only and transport-free: no aiohttp, no engine
imports — the gateway owns sockets and scheduling, this module owns
bytes. Prompts may arrive as text OR as raw token-id lists (the
OpenAI completions API allows both); without a real tokenizer the
text path uses a byte-level codec (UTF-8 bytes as token ids), which is
exact against the 256-vocab harness models and a documented
approximation elsewhere — production deployments inject a tokenizer
pair into the gateway instead.
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Any, Dict, List, Optional

from areal_tpu.base.wire_schemas import GATEWAY_V1


class PublicApiError(Exception):
    """A client-visible request defect: maps to a 4xx with a JSON error
    body (never a stack trace on the wire)."""

    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message


# -- prompt codec (tokenizer-free fallback) -------------------------------

def encode_text(text: str) -> List[int]:
    """Byte-level text -> token ids (UTF-8 bytes). Identity-exact for
    vocab-256 harness models; a real tokenizer replaces this via the
    gateway's ``tokenizer`` hook."""
    return list(text.encode("utf-8"))


def decode_tokens(token_ids: List[int]) -> str:
    """Token ids -> display text for SSE chunks. Ids outside the byte
    range are folded (& 0xFF): display fidelity only, the authoritative
    payload is always the ``token_ids`` field alongside."""
    return bytes(int(t) & 0xFF for t in token_ids).decode(
        "utf-8", errors="replace"
    )


# -- request parsing ------------------------------------------------------

@dataclasses.dataclass
class ParsedRequest:
    kind: str  # "completion" | "chat"
    model: str
    prompt_ids: List[int]
    max_tokens: int
    stream: bool
    temperature: float
    top_p: float
    greedy: bool
    # Optional client session key: requests sharing one ride the
    # manager's prefix-affinity routing (multi-turn tenants keep their
    # parked KV + kv_source hints).
    session: Optional[str] = None


def _prompt_to_ids(prompt: Any) -> List[int]:
    if isinstance(prompt, str):
        return encode_text(prompt)
    if isinstance(prompt, list):
        if all(isinstance(t, int) for t in prompt):
            return [int(t) for t in prompt]
        if len(prompt) == 1 and isinstance(prompt[0], str):
            return encode_text(prompt[0])
        raise PublicApiError(
            400, "prompt must be a string, a token-id list, or a "
                 "single-element string list (batched prompts are not "
                 "supported)"
        )
    raise PublicApiError(400, f"unsupported prompt type {type(prompt).__name__}")


def _common_fields(body: Dict[str, Any], kind: str,
                   prompt_ids: List[int]) -> ParsedRequest:
    if not prompt_ids:
        raise PublicApiError(400, "empty prompt")
    try:
        max_tokens = int(body.get("max_tokens", 16))
        temperature = float(body.get("temperature", 1.0))
        top_p = float(body.get("top_p", 1.0))
    except (TypeError, ValueError) as e:
        raise PublicApiError(400, f"bad sampling field: {e}") from None
    if max_tokens < 1:
        raise PublicApiError(400, "max_tokens must be >= 1")
    n = body.get("n", 1)
    if n not in (1, None):
        raise PublicApiError(400, "n > 1 is not supported")
    session = body.get("session")
    if session is not None and not isinstance(session, str):
        raise PublicApiError(400, "session must be a string")
    return ParsedRequest(
        kind=kind,
        model=str(body.get("model") or "areal"),
        prompt_ids=prompt_ids,
        max_tokens=max_tokens,
        stream=bool(body.get("stream", True)),
        temperature=temperature,
        top_p=top_p,
        greedy=bool(body.get("greedy", temperature == 0.0)),
        session=session,
    )


def parse_completion_request(body: Dict[str, Any]) -> ParsedRequest:
    if not isinstance(body, dict):
        raise PublicApiError(400, "request body must be a JSON object")
    if "prompt" not in body:
        raise PublicApiError(400, "missing 'prompt'")
    return _common_fields(body, "completion", _prompt_to_ids(body["prompt"]))


def render_chat_prompt(messages: List[Dict[str, Any]]) -> str:
    """Flatten a chat transcript into one prompt string. Minimal
    role-tagged template — the byte codec (or an injected tokenizer)
    sees exactly this text."""
    lines = []
    for m in messages:
        role = m.get("role", "user")
        content = m.get("content", "")
        if not isinstance(content, str):
            raise PublicApiError(400, "message content must be a string")
        lines.append(f"{role}: {content}")
    lines.append("assistant:")
    return "\n".join(lines)


def parse_chat_request(body: Dict[str, Any]) -> ParsedRequest:
    if not isinstance(body, dict):
        raise PublicApiError(400, "request body must be a JSON object")
    messages = body.get("messages")
    if not isinstance(messages, list) or not messages:
        raise PublicApiError(400, "missing or empty 'messages'")
    prompt_ids = encode_text(render_chat_prompt(messages))
    return _common_fields(body, "chat", prompt_ids)


# -- response framing -----------------------------------------------------

SSE_DONE = b"data: [DONE]\n\n"


def sse_event(payload: Dict[str, Any]) -> bytes:
    return b"data: " + json.dumps(
        payload, separators=(",", ":")
    ).encode() + b"\n\n"


def _base_obj(request_id: str, model: str, obj: str) -> Dict[str, Any]:
    return {
        "schema": GATEWAY_V1,
        "id": request_id,
        "object": obj,
        "created": int(time.time()),
        "model": model,
    }


def completion_chunk(request_id: str, model: str, token_ids: List[int],
                     finish_reason: Optional[str] = None) -> Dict[str, Any]:
    out = _base_obj(request_id, model, "text_completion.chunk")
    out["choices"] = [{
        "index": 0,
        "text": decode_tokens(token_ids),
        "token_ids": [int(t) for t in token_ids],
        "finish_reason": finish_reason,
    }]
    return out


def chat_chunk(request_id: str, model: str, token_ids: List[int],
               first: bool = False,
               finish_reason: Optional[str] = None) -> Dict[str, Any]:
    delta: Dict[str, Any] = {"content": decode_tokens(token_ids)}
    if first:
        delta["role"] = "assistant"
    out = _base_obj(request_id, model, "chat.completion.chunk")
    out["choices"] = [{
        "index": 0,
        "delta": delta,
        "token_ids": [int(t) for t in token_ids],
        "finish_reason": finish_reason,
    }]
    return out


def usage_fields(prompt_tokens: int, completion_tokens: int
                 ) -> Dict[str, int]:
    return {
        "prompt_tokens": int(prompt_tokens),
        "completion_tokens": int(completion_tokens),
        "total_tokens": int(prompt_tokens) + int(completion_tokens),
    }


def completion_body(request_id: str, model: str, token_ids: List[int],
                    prompt_tokens: int, finish_reason: str
                    ) -> Dict[str, Any]:
    """Non-streaming aggregate response (stream=false)."""
    out = _base_obj(request_id, model, "text_completion")
    out["choices"] = [{
        "index": 0,
        "text": decode_tokens(token_ids),
        "token_ids": [int(t) for t in token_ids],
        "finish_reason": finish_reason,
    }]
    out["usage"] = usage_fields(prompt_tokens, len(token_ids))
    return out


def chat_body(request_id: str, model: str, token_ids: List[int],
              prompt_tokens: int, finish_reason: str) -> Dict[str, Any]:
    out = _base_obj(request_id, model, "chat.completion")
    out["choices"] = [{
        "index": 0,
        "message": {"role": "assistant",
                    "content": decode_tokens(token_ids)},
        "token_ids": [int(t) for t in token_ids],
        "finish_reason": finish_reason,
    }]
    out["usage"] = usage_fields(prompt_tokens, len(token_ids))
    return out


def error_body(status: int, message: str,
               retry_after: Optional[float] = None) -> Dict[str, Any]:
    err: Dict[str, Any] = {
        "message": message,
        "type": {400: "invalid_request_error",
                 401: "authentication_error",
                 429: "rate_limit_error"}.get(status, "api_error"),
        "code": status,
    }
    if retry_after is not None:
        err["retry_after"] = float(retry_after)
    return {"schema": GATEWAY_V1, "error": err}

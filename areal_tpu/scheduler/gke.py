"""GKE/XPK-style Kubernetes scheduler client.

The TPU-native counterpart of the reference's SLURM backend
(realhf/scheduler/slurm/client.py:78 submits sbatch/srun-multiprog worker
arrays with container images; slurm/utils.py renders the scripts): on
Google Cloud, TPU pod workloads are Kubernetes Jobs on GKE node pools
(what the XPK tool generates), so this client renders one k8s Job per
worker and drives it through `kubectl` — submit = `kubectl apply`,
find = `kubectl get job -o json`, stop = `kubectl delete job`.

Design notes:
- One Job per worker (completions=1, backoffLimit=0, restartPolicy=Never).
  Pod-level retry is deliberately OFF: the framework's own relaunch loop
  (training/utils.py:run_experiment) owns failure recovery, because a
  worker restart without the master's recover protocol would desync the
  experiment (same reason the reference passes SLURM `--no-requeue`).
- TPU placement follows GKE's conventions: `google.com/tpu` resource
  requests plus `cloud.google.com/gke-tpu-accelerator` /
  `cloud.google.com/gke-tpu-topology` node selectors.
- `kubectl_cmd` is injectable so tests fake the cluster at the
  subprocess boundary (the same place the reference's tests fake sbatch).
"""

from __future__ import annotations

import json
import re
import subprocess
import threading
import time
from typing import Dict, List, Optional

from areal_tpu.base import logging
from areal_tpu.scheduler.client import (
    JobException,
    JobInfo,
    JobState,
    SchedulerClient,
    register_scheduler,
)

logger = logging.getLogger("gke_scheduler")


def k8s_name(name: str) -> str:
    """RFC 1123 DNS label: lowercase alphanumerics and '-', max 63 chars.
    Worker names like 'model_worker/3' become 'model-worker-3'."""
    s = re.sub(r"[^a-z0-9-]+", "-", name.lower()).strip("-")
    return s[:63].rstrip("-") or "job"


class KubernetesSchedulerClient(SchedulerClient):
    def __init__(
        self,
        namespace: str = "default",
        container_image: str = "python:3.12-slim",
        tpu_type: Optional[str] = None,
        tpu_topology: Optional[str] = None,
        tpu_chips_per_pod: int = 0,
        host_network: bool = True,
        kubectl_cmd: str = "kubectl",
        name_prefix: str = "",
        labels: Optional[Dict[str, str]] = None,
        volumes: Optional[List[Dict]] = None,
        volume_mounts: Optional[List[Dict]] = None,
        log_dir: Optional[str] = None,  # accepted for registry parity
    ):
        self.namespace = namespace
        self.container_image = container_image
        self.tpu_type = tpu_type
        self.tpu_topology = tpu_topology
        self.tpu_chips_per_pod = tpu_chips_per_pod
        self.host_network = host_network
        self.kubectl_cmd = kubectl_cmd
        # Scopes job names per experiment/trial (the reference's SLURM
        # job names embed experiment+trial the same way) so concurrent
        # trials in one namespace can't collide — submit()'s stale-job
        # cleanup would otherwise delete another trial's live workers.
        self.name_prefix = name_prefix
        self.labels = dict(labels or {})
        self.volumes = volumes or []
        self.volume_mounts = volume_mounts or []
        # logical name -> k8s job name
        self._jobs: Dict[str, str] = {}

    # -- kubectl plumbing ------------------------------------------------

    def _job_name(self, name: str) -> str:
        scoped = f"{self.name_prefix}-{name}" if self.name_prefix else name
        return k8s_name(scoped)

    def _kubectl(
        self, args: List[str], stdin: Optional[str] = None
    ) -> subprocess.CompletedProcess:
        cmd = [self.kubectl_cmd, "-n", self.namespace, *args]
        return subprocess.run(
            cmd, input=stdin, capture_output=True, text=True, timeout=120
        )

    # -- manifest --------------------------------------------------------

    def _manifest(
        self,
        job_name: str,
        logical_name: str,
        cmd: List[str],
        env: Optional[Dict[str, str]],
        cwd: Optional[str],
    ) -> Dict:
        container: Dict = {
            "name": "worker",
            "image": self.container_image,
            "command": list(cmd),
            "env": [
                {"name": k, "value": str(v)} for k, v in (env or {}).items()
            ],
        }
        if cwd:
            container["workingDir"] = cwd
        if self.volume_mounts:
            container["volumeMounts"] = self.volume_mounts
        if self.tpu_chips_per_pod:
            container["resources"] = {
                "requests": {"google.com/tpu": self.tpu_chips_per_pod},
                "limits": {"google.com/tpu": self.tpu_chips_per_pod},
            }
        pod_spec: Dict = {
            "restartPolicy": "Never",
            "containers": [container],
        }
        if self.host_network:
            # Workers discover each other by host ip:port through the KV
            # name service; host networking keeps those addresses stable.
            pod_spec["hostNetwork"] = True
            pod_spec["dnsPolicy"] = "ClusterFirstWithHostNet"
        selector = {}
        if self.tpu_type:
            selector["cloud.google.com/gke-tpu-accelerator"] = self.tpu_type
        if self.tpu_topology:
            selector["cloud.google.com/gke-tpu-topology"] = self.tpu_topology
        if selector:
            pod_spec["nodeSelector"] = selector
        if self.volumes:
            pod_spec["volumes"] = self.volumes
        labels = {
            **self.labels,
            "app.kubernetes.io/managed-by": "areal-tpu",
            "areal-tpu/worker": k8s_name(logical_name),
        }
        return {
            "apiVersion": "batch/v1",
            "kind": "Job",
            "metadata": {
                "name": job_name,
                "namespace": self.namespace,
                "labels": labels,
            },
            "spec": {
                "completions": 1,
                "parallelism": 1,
                "backoffLimit": 0,
                "template": {
                    "metadata": {"labels": labels},
                    "spec": pod_spec,
                },
            },
        }

    # -- SchedulerClient API ---------------------------------------------

    def submit(
        self,
        name: str,
        cmd: List[str],
        env: Optional[Dict[str, str]] = None,
        cwd: Optional[str] = None,
        **kwargs,
    ) -> str:
        job_name = self._job_name(name)
        if name in self._jobs:
            state = self.find(name).state
            if state in (JobState.PENDING, JobState.RUNNING):
                raise ValueError(f"job {name!r} already running")
        # A stale same-name Job from a previous (failed) attempt blocks
        # `apply` on immutable pod-template fields — recovery relaunches
        # reuse worker names, so clear it first (k8s Jobs are one-shot).
        self._kubectl(
            ["delete", "job", job_name, "--ignore-not-found", "--wait=true"]
        )
        manifest = self._manifest(job_name, name, cmd, env, cwd)
        r = self._kubectl(["apply", "-f", "-"], stdin=json.dumps(manifest))
        if r.returncode != 0:
            raise RuntimeError(
                f"kubectl apply failed for {name}: {r.stderr.strip()}"
            )
        self._jobs[name] = job_name
        logger.info(f"submitted k8s job {job_name} for worker {name}")
        return name

    def find(self, name: str) -> JobInfo:
        job_name = self._jobs.get(name, self._job_name(name))
        r = self._kubectl(["get", "job", job_name, "-o", "json"])
        if r.returncode != 0:
            if "NotFound" in r.stderr or "not found" in r.stderr:
                return JobInfo(name, JobState.NOT_FOUND)
            raise RuntimeError(
                f"kubectl get failed for {name}: {r.stderr.strip()}"
            )
        status = json.loads(r.stdout).get("status", {})
        if status.get("succeeded", 0) >= 1:
            return JobInfo(name, JobState.COMPLETED, exit_code=0)
        if status.get("failed", 0) >= 1:
            return JobInfo(name, JobState.FAILED, exit_code=1)
        if status.get("active", 0) >= 1:
            return JobInfo(name, JobState.RUNNING)
        return JobInfo(name, JobState.PENDING)

    def wait(
        self,
        names: Optional[List[str]] = None,
        timeout: Optional[float] = None,
        raise_on_failure: bool = True,
        poll_interval: float = 2.0,
    ) -> List[JobInfo]:
        names = list(names) if names is not None else list(self._jobs)
        deadline = None if timeout is None else time.monotonic() + timeout
        terminal = (
            JobState.COMPLETED,
            JobState.FAILED,
            JobState.CANCELLED,
            JobState.NOT_FOUND,
        )
        while True:
            infos = [self.find(n) for n in names]
            if raise_on_failure:
                for i in infos:
                    if i.state in (JobState.FAILED, JobState.CANCELLED):
                        raise JobException(i)
            if all(i.state in terminal for i in infos):
                return infos
            if deadline is not None and time.monotonic() > deadline:
                running = [
                    i.name for i in infos if i.state not in terminal
                ]
                raise TimeoutError(f"jobs still running: {running}")
            time.sleep(poll_interval)

    def stop(self, name: str):
        job_name = self._jobs.get(name, self._job_name(name))
        r = self._kubectl(
            ["delete", "job", job_name, "--ignore-not-found", "--wait=false"]
        )
        if r.returncode != 0:
            logger.warning(
                f"kubectl delete failed for {name}: {r.stderr.strip()}"
            )

    def stop_all(self):
        for name in list(self._jobs):
            self.stop(name)
        self._jobs.clear()


register_scheduler("gke", KubernetesSchedulerClient)


class GkeLauncher:
    """Elastic-fleet actuation on GKE: the
    :class:`areal_tpu.system.fleet_controller.Launcher` protocol
    implemented over :class:`KubernetesSchedulerClient` (closes the
    ROADMAP item-1 remainder — local subprocess actuation was the only
    Launcher until now).

    Each ``launch(server_index)`` submits one k8s Job running the
    generation-server entrypoint (``cmd_fn(server_index)``); the server
    registers itself through the normal name_resolve discovery path, so
    the manager's join protocol is unchanged. ``stop(handle)`` deletes
    the Job — best-effort, the graceful path is the manager's /drain.
    ``reap()`` forgets terminal Jobs and records failures so a crashed
    scale-out is distinguishable from a deliberate scale-in.

    Duck-typed rather than inheriting fleet_controller.Launcher to keep
    the scheduler package import-light (the protocol is structural —
    the manager only ever calls launch/stop/reap)."""

    def __init__(
        self,
        client: KubernetesSchedulerClient,
        cmd_fn,
        env_fn=None,
        name_fn=None,
    ):
        self.client = client
        self._cmd_fn = cmd_fn
        self._env_fn = env_fn
        self._name_fn = name_fn or (lambda i: f"gen-server-{i}")
        self._lock = threading.Lock()
        # logical job name -> server index, for reap bookkeeping.
        self.launched: Dict[str, int] = {}
        # Jobs that reached FAILED before being forgotten.
        self.failures: List[str] = []

    def launch(self, server_index: int) -> str:
        """Submit the Job; returns its logical name (the stop handle).
        Raises on kubectl/apply failure — the fleet controller treats a
        raise as an unactuated decision and retries next poll."""
        name = self._name_fn(server_index)
        env = self._env_fn(server_index) if self._env_fn else None
        self.client.submit(name, self._cmd_fn(server_index), env=env)
        with self._lock:
            self.launched[name] = int(server_index)
        logger.info(
            f"launched GKE generation server index {server_index} "
            f"as job {name!r}"
        )
        return name

    def stop(self, handle: str) -> None:
        try:
            self.client.stop(handle)
        except Exception:
            logger.warning(f"GKE stop failed for {handle!r}", exc_info=True)

    def reap(self) -> None:
        """Forget terminal Jobs (completed, cancelled, vanished) and
        record failed ones. A kubectl flake skips the job until the
        next poll instead of misclassifying it."""
        with self._lock:
            names = list(self.launched)
        terminal = (
            JobState.COMPLETED,
            JobState.FAILED,
            JobState.CANCELLED,
            JobState.NOT_FOUND,
        )
        for name in names:
            try:
                info = self.client.find(name)
            except Exception:
                continue
            if info.state in terminal:
                with self._lock:
                    self.launched.pop(name, None)
                    if info.state == JobState.FAILED:
                        self.failures.append(name)

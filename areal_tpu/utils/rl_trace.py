"""RL-trace aggregation: merge per-worker span shards into one timeline.

Counterpart of nothing in the reference — realhf has per-worker logs and
XLA profiles only. This module consumes the JSONL shards written by
`areal_tpu/base/tracing.py` (one per worker process under
AREAL_RL_TRACE_DIR) and produces:

- one Chrome-trace/Perfetto JSON: a process track per worker, an X slice
  per span, and flow events stitching each rollout's spans across
  processes (plus train-consumption links from every rollout trace into
  the train-step MFC slice that consumed it);
- derived reports: a staleness histogram (policy-version lag at
  consumption), a per-phase latency breakdown (queue-wait / prefill /
  decode / interrupted-re-prefill / reward / buffer-wait / train), and
  an overlap score — the fraction of the run's wall span during which a
  generation track and a training track are simultaneously busy, i.e.
  the direct evidence for (or against) rollout/train overlap.

Shards record monotonic-ns timestamps plus one (wall, monotonic) anchor
pair in the header; merging maps every span onto the shared wall clock,
so cross-worker alignment is as good as host clock sync.

CLI: scripts/merge_rl_trace.py. Span model: docs/observability.md.
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

# Span names whose wall time counts as "generation busy" for the overlap
# score. Engine-level batch spans are preferred (true device busy); the
# per-request server.generate span is the fallback when the engine was
# not instrumented (fake servers in harness tests).
GEN_BUSY_NAMES = ("server.prefill", "server.decode_block")
GEN_BUSY_FALLBACK = ("server.generate",)

# Friendly phase -> span names feeding it, in report order. queue_wait
# is the client-side admission span (the manager's allocate/schedule
# records are zero-duration events — counts, not latencies).
PHASE_NAMES: List[Tuple[str, Tuple[str, ...]]] = [
    ("rollout_e2e", ("rollout.episode",)),
    ("queue_wait", ("rollout.allocate",)),
    ("generate", ("gen.sample",)),
    ("gen_chunk", ("gen.chunk",)),
    ("prefill", ("server.prefill",)),
    ("decode", ("server.decode_block",)),
    ("server_generate", ("server.generate",)),
    ("reward", ("reward.verify",)),
    ("stream_recv", ("stream.recv",)),
    ("buffer_wait", ("buffer.wait",)),
    # Kept separate: the fanout span CONTAINS the per-server spans, so
    # one merged phase would double-count the same wall interval.
    ("weight_update_fanout", ("manager.weight_update",)),
    ("weight_update_server", ("server.weight_update",)),
    ("train", ()),  # resolved by _is_train below
]


@dataclasses.dataclass
class Shard:
    path: str
    header: Dict[str, Any]
    spans: List[Dict[str, Any]]
    n_dropped: int = 0
    problems: List[str] = dataclasses.field(default_factory=list)

    @property
    def worker(self) -> str:
        return str(self.header.get("worker", os.path.basename(self.path)))


_SPAN_REQUIRED = ("name", "trace", "span", "start_ns", "end_ns")


def load_shard(path: str) -> Shard:
    """Parse one shard, collecting (not raising on) well-formedness
    problems so a single corrupt line doesn't hide the rest."""
    header: Dict[str, Any] = {}
    spans: List[Dict[str, Any]] = []
    problems: List[str] = []
    n_dropped = 0
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                problems.append(f"{path}:{lineno}: bad json ({e})")
                continue
            kind = rec.get("kind")
            if kind == "header":
                header = rec
            elif kind == "dropped":
                n_dropped += int(rec.get("count", 0))
            elif kind == "span":
                missing = [k for k in _SPAN_REQUIRED if k not in rec]
                if missing:
                    problems.append(
                        f"{path}:{lineno}: span missing {missing}"
                    )
                    continue
                if rec["end_ns"] < rec["start_ns"]:
                    problems.append(
                        f"{path}:{lineno}: span {rec['span']} ends before "
                        f"it starts"
                    )
                    continue
                spans.append(rec)
            else:
                problems.append(f"{path}:{lineno}: unknown kind {kind!r}")
    if not header:
        problems.append(f"{path}: missing header line")
    return Shard(
        path=path, header=header, spans=spans, n_dropped=n_dropped,
        problems=problems,
    )


def load_shards(trace_dir: str) -> List[Shard]:
    paths = sorted(glob.glob(os.path.join(trace_dir, "*.jsonl")))
    if not paths:
        raise FileNotFoundError(f"no trace shards (*.jsonl) under {trace_dir}")
    return [load_shard(p) for p in paths]


WAIVED_PREFIX = "waived (ring overflow recorded): "


def validate(shards: List[Shard]) -> List[str]:
    """Global well-formedness: per-shard parse problems plus dangling
    span references — a parent id that no span in the SAME trace defines
    anywhere across the fleet (parents legitimately live in other
    shards: a buffer.wait span's parent is the rollout worker's episode
    span).

    When any shard recorded ring-buffer drops, dangling parents are the
    EXPECTED consequence of the by-design overflow policy, so those
    findings are prefixed with WAIVED_PREFIX — consumers (the merge
    script) report them without failing the run. A dangling parent with
    zero recorded drops is a genuine emitter bug and stays fatal."""
    problems = [p for s in shards for p in s.problems]
    overflowed = any(s.n_dropped > 0 for s in shards)
    by_trace: Dict[str, set] = {}
    for s in shards:
        for sp in s.spans:
            by_trace.setdefault(sp["trace"], set()).add(sp["span"])
    for s in shards:
        for sp in s.spans:
            parent = sp.get("parent")
            if parent and parent not in by_trace.get(sp["trace"], ()):
                problems.append(
                    (WAIVED_PREFIX if overflowed else "")
                    + f"{s.path}: span {sp['span']} ({sp['name']}) "
                    f"references dangling parent {parent} in trace "
                    f"{sp['trace']}"
                )
    return problems


# ---------------------------------------------------------------------------
# Wall-clock mapping + merge
# ---------------------------------------------------------------------------


def _to_wall_us(shard: Shard, mono_ns: int) -> float:
    h = shard.header
    wall = int(h.get("anchor_wall_ns", 0))
    mono = int(h.get("anchor_mono_ns", 0))
    return (wall + (int(mono_ns) - mono)) / 1e3


def _is_train(span: Dict[str, Any]) -> bool:
    """Any train-step MFC span, master- or worker-side (consumption
    links: the master-side span carries `consumed_traces`)."""
    attrs = span.get("attrs") or {}
    return (
        span["name"].startswith(("mfc.", "master.mfc."))
        and attrs.get("itype") == "train_step"
    )


def _is_train_exec(span: Dict[str, Any]) -> bool:
    """Worker-side train EXECUTION only (`mfc.*`, not `master.mfc.*`):
    the master span additionally covers dispatch/transport wait and
    duplicates every worker span's interval, so latency and overlap
    accounting must not mix the two."""
    attrs = span.get("attrs") or {}
    return (
        span["name"].startswith("mfc.")
        and attrs.get("itype") == "train_step"
    )


def _flow_id(trace_id: str) -> int:
    try:
        return int(str(trace_id)[:12], 16) & 0x7FFFFFFF
    except ValueError:
        return abs(hash(trace_id)) & 0x7FFFFFFF


def merge_to_chrome(shards: List[Shard]) -> Dict[str, Any]:
    """One Chrome-trace JSON: process track per worker, X slice per span,
    `s`/`t` flow steps per trace id (in start order, across processes),
    and extra flow steps from each consumed rollout trace into the train
    slice that consumed it (attr `consumed_traces`)."""
    events: List[Dict[str, Any]] = []
    # Deterministic pid assignment: sorted worker names.
    order = sorted(range(len(shards)), key=lambda i: shards[i].worker)
    located: Dict[str, List[Tuple[float, Dict, int, int]]] = {}
    for pid, i in enumerate(order):
        shard = shards[i]
        events.append(
            {
                "ph": "M", "pid": pid, "tid": 0, "name": "process_name",
                "args": {"name": shard.worker},
            }
        )
        for sp in shard.spans:
            ts = _to_wall_us(shard, sp["start_ns"])
            dur = max(0.001, (sp["end_ns"] - sp["start_ns"]) / 1e3)
            tid = int(sp.get("tid", 0))
            args = dict(sp.get("attrs") or {})
            args["trace_id"] = sp["trace"]
            args["span_id"] = sp["span"]
            if sp.get("parent"):
                args["parent_id"] = sp["parent"]
            events.append(
                {
                    "ph": "X", "pid": pid, "tid": tid, "ts": ts, "dur": dur,
                    "name": sp["name"], "cat": "rl",
                    "args": args,
                }
            )
            located.setdefault(sp["trace"], []).append((ts, sp, pid, tid))

    # Consumption links join each consumed rollout's chain as an extra
    # step ON the train slice BEFORE the chain is emitted — Chrome's
    # flow contract forbids steps after the finish event, so the train
    # slice must become part of the ts-ordered chain, not a late `t`.
    for pid, i in enumerate(order):
        shard = shards[i]
        for sp in shard.spans:
            if not _is_train(sp):
                continue
            consumed = (sp.get("attrs") or {}).get("consumed_traces") or []
            ts = _to_wall_us(shard, sp["start_ns"])
            tid = int(sp.get("tid", 0))
            for tr in consumed:
                if tr in located:
                    located[tr].append((ts + 0.001, None, pid, tid))

    # Flow events: one chain per trace in step start order (`s` at the
    # first step, `t` between, `f` at the last).
    for trace_id, items in located.items():
        if len(items) < 2:
            continue
        items.sort(key=lambda t: t[0])
        fid = _flow_id(trace_id)
        for j, (ts, _sp, pid, tid) in enumerate(items):
            events.append(
                {
                    "ph": "s" if j == 0 else ("f" if j == len(items) - 1 else "t"),
                    "id": fid, "pid": pid, "tid": tid, "ts": ts,
                    "name": "rollout", "cat": "rl.flow",
                    **({"bp": "e"} if j == len(items) - 1 else {}),
                }
            )
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# ---------------------------------------------------------------------------
# Derived reports
# ---------------------------------------------------------------------------


def _wall_intervals(
    shards: List[Shard], names: Tuple[str, ...]
) -> List[Tuple[float, float]]:
    out = []
    for s in shards:
        for sp in s.spans:
            if sp["name"] in names:
                t0 = _to_wall_us(s, sp["start_ns"])
                out.append((t0, t0 + (sp["end_ns"] - sp["start_ns"]) / 1e3))
    return out


def _union(intervals: List[Tuple[float, float]]) -> List[Tuple[float, float]]:
    if not intervals:
        return []
    intervals = sorted(intervals)
    merged = [list(intervals[0])]
    for s, e in intervals[1:]:
        if s <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], e)
        else:
            merged.append([s, e])
    return [(s, e) for s, e in merged]


def _total(intervals: List[Tuple[float, float]]) -> float:
    return sum(e - s for s, e in intervals)


def _intersect(
    a: List[Tuple[float, float]], b: List[Tuple[float, float]]
) -> float:
    total, i, j = 0.0, 0, 0
    while i < len(a) and j < len(b):
        s = max(a[i][0], b[j][0])
        e = min(a[i][1], b[j][1])
        if s < e:
            total += e - s
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return total


def overlap_score(shards: List[Shard]) -> Dict[str, float]:
    """Fraction of the run's wall span where generation and training are
    simultaneously busy (interval-union per side, so parallel servers /
    DP workers don't double-count)."""
    gen_iv = _wall_intervals(shards, GEN_BUSY_NAMES)
    if not gen_iv:
        gen_iv = _wall_intervals(shards, GEN_BUSY_FALLBACK)

    def _train_iv(pred):
        return [
            (
                _to_wall_us(s, sp["start_ns"]),
                _to_wall_us(s, sp["start_ns"])
                + (sp["end_ns"] - sp["start_ns"]) / 1e3,
            )
            for s in shards
            for sp in s.spans
            if pred(sp)
        ]

    # Worker-side execution spans; master-side dispatch spans only as a
    # fallback when no worker instrumented the run.
    train_iv = _train_iv(_is_train_exec) or _train_iv(_is_train)
    gen_u, train_u = _union(gen_iv), _union(train_iv)
    all_iv = _union(gen_u + train_u)
    wall = (all_iv[-1][1] - all_iv[0][0]) if all_iv else 0.0
    both = _intersect(gen_u, train_u)
    return {
        "overlap_score": both / wall if wall > 0 else 0.0,
        "gen_busy_frac": _total(gen_u) / wall if wall > 0 else 0.0,
        "train_busy_frac": _total(train_u) / wall if wall > 0 else 0.0,
        "both_busy_s": both / 1e6,
        "wall_s": wall / 1e6,
    }


def staleness_histogram(shards: List[Shard]) -> Dict[int, int]:
    """Policy-version lag at consumption: train_step − version_start over
    buffer.wait spans (generation started `k` published versions before
    the step that trained on it). Buckets are exact integer lags.

    Multi-MFC graphs record one buffer.wait per consuming MFC; samples
    are counted ONCE each — by their LAST consumption (the span ending
    latest), which is the step that exhausted them."""
    last_per_sample: Dict[str, Tuple[int, int]] = {}  # sid -> (end, lag)
    for s in shards:
        for sp in s.spans:
            if sp["name"] != "buffer.wait":
                continue
            attrs = sp.get("attrs") or {}
            v0 = attrs.get("version_start")
            step = attrs.get("train_step")
            if v0 is None or step is None or int(v0) < 0:
                continue
            lag = max(0, int(step) - int(v0))
            sid = str(attrs.get("sample_id") or sp["span"])
            prev = last_per_sample.get(sid)
            if prev is None or sp["end_ns"] > prev[0]:
                last_per_sample[sid] = (sp["end_ns"], lag)
    hist: Dict[int, int] = {}
    for _end, lag in last_per_sample.values():
        hist[lag] = hist.get(lag, 0) + 1
    return dict(sorted(hist.items()))


def phase_latency(shards: List[Shard]) -> Dict[str, Dict[str, float]]:
    """Per-phase latency stats (ms): count / p50 / p95 / total, keyed by
    the friendly phase names in PHASE_NAMES, plus the re-prefill cost of
    interruption (tokens resubmitted for prefill after an interrupt or
    chunk boundary) as `interrupted_reprefill`."""
    durs: Dict[str, List[float]] = {}
    reprefill_tokens = 0.0
    n_interrupted = 0
    for s in shards:
        for sp in s.spans:
            ms = (sp["end_ns"] - sp["start_ns"]) / 1e6
            attrs = sp.get("attrs") or {}
            if sp["name"] == "gen.chunk":
                reprefill_tokens += float(attrs.get("reprefill_tokens", 0))
            elif sp["name"] == "gen.interrupted":
                n_interrupted += 1
            if _is_train(sp):
                # Worker-side execution only; the master-side span over
                # the same step would double-count and fold transport
                # wait into "train".
                if _is_train_exec(sp):
                    durs.setdefault("train", []).append(ms)
                continue
            for phase, names in PHASE_NAMES:
                if sp["name"] in names:
                    durs.setdefault(phase, []).append(ms)
                    break
    out: Dict[str, Dict[str, float]] = {}
    for phase, _ in PHASE_NAMES:
        vals = durs.get(phase)
        if not vals:
            continue
        arr = np.asarray(vals)
        out[phase] = {
            "count": float(arr.size),
            "p50_ms": float(np.percentile(arr, 50)),
            "p95_ms": float(np.percentile(arr, 95)),
            "total_ms": float(arr.sum()),
        }
    out["interrupted_reprefill"] = {
        "count": float(n_interrupted),
        "tokens": reprefill_tokens,
    }
    return out


def rollout_latency_stats(shards: List[Shard]) -> Dict[str, float]:
    """Rollout end-to-end latency percentiles over rollout.episode spans."""
    vals = [
        (sp["end_ns"] - sp["start_ns"]) / 1e6
        for s in shards
        for sp in s.spans
        if sp["name"] == "rollout.episode"
    ]
    if not vals:
        return {}
    arr = np.asarray(vals)
    return {
        "rollout_e2e_p50_ms": float(np.percentile(arr, 50)),
        "rollout_e2e_p95_ms": float(np.percentile(arr, 95)),
        "rollout_count": float(arr.size),
    }


def summarize(trace_dir: str) -> Dict[str, Any]:
    """Everything a perf consumer wants in one dict (master perf_summary
    / bench JSON): overlap score, staleness histogram, phase breakdown,
    rollout latency percentiles."""
    return summarize_shards(load_shards(trace_dir))


def summarize_shards(shards: List[Shard]) -> Dict[str, Any]:
    out: Dict[str, Any] = {
        "n_shards": len(shards),
        "n_spans": sum(len(s.spans) for s in shards),
        "n_dropped": sum(s.n_dropped for s in shards),
    }
    out.update(overlap_score(shards))
    out.update(rollout_latency_stats(shards))
    out["staleness_hist"] = {
        str(k): v for k, v in staleness_histogram(shards).items()
    }
    out["phases"] = phase_latency(shards)
    reprefill = out["phases"].get("interrupted_reprefill", {})
    out["reprefill_tokens"] = float(reprefill.get("tokens", 0.0))
    return out


def format_report(shards: List[Shard]) -> str:
    ov = overlap_score(shards)
    hist = staleness_histogram(shards)
    phases = phase_latency(shards)
    roll = rollout_latency_stats(shards)
    lines = [
        f"shards: {len(shards)}   spans: "
        f"{sum(len(s.spans) for s in shards)}   dropped: "
        f"{sum(s.n_dropped for s in shards)}",
        "",
        f"overlap score: {ov['overlap_score']:.3f}  "
        f"(gen busy {ov['gen_busy_frac']:.3f}, "
        f"train busy {ov['train_busy_frac']:.3f}, "
        f"wall {ov['wall_s']:.2f}s)",
    ]
    if roll:
        lines.append(
            f"rollout e2e: p50 {roll['rollout_e2e_p50_ms']:.1f} ms  "
            f"p95 {roll['rollout_e2e_p95_ms']:.1f} ms  "
            f"(n={int(roll['rollout_count'])})"
        )
    lines.append("")
    lines.append("staleness histogram (train_step - version_start):")
    if hist:
        width = max(hist.values())
        for lag, n in hist.items():
            bar = "#" * max(1, round(30 * n / width))
            lines.append(f"  lag {lag:>3}: {n:>6}  {bar}")
    else:
        lines.append("  (no buffer.wait spans with version attrs)")
    lines.append("")
    lines.append("per-phase latency breakdown:")
    lines.append(
        f"  {'phase':<22}{'count':>8}{'p50 ms':>12}{'p95 ms':>12}"
        f"{'total ms':>12}"
    )
    for phase, st in phases.items():
        if phase == "interrupted_reprefill":
            continue
        lines.append(
            f"  {phase:<22}{int(st['count']):>8}{st['p50_ms']:>12.2f}"
            f"{st['p95_ms']:>12.2f}{st['total_ms']:>12.1f}"
        )
    rp = phases.get("interrupted_reprefill", {})
    lines.append(
        f"  interrupted re-prefill: {int(rp.get('count', 0))} interrupt(s), "
        f"{rp.get('tokens', 0):.0f} tokens resubmitted"
    )
    return "\n".join(lines)

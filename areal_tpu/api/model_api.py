"""Model, engine, interface and backend contracts + registries.

Counterpart of the reference's model API (realhf/api/core/model_api.py).
The central engine abstraction (`TrainEngine`, mirroring the reference's
`PipelinableEngine:514`) is what algorithm interfaces program against:
`train_batch` / `forward` / `generate` over packed `SequenceSample`s with
micro-batch specs. On TPU an engine owns a pytree of sharded params on a
`jax.sharding.Mesh` and jitted step functions — there is no per-rank
pipelining object; GSPMD replaces the reference's pipe runner.
"""

from __future__ import annotations

import abc
import dataclasses
import keyword
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from areal_tpu.api.config import (
    ModelAbstraction,
    ModelBackendAbstraction,
    ModelFamily,
    ModelInterfaceAbstraction,
    ModelName,
    Registry,
)
from areal_tpu.api.data_api import MicroBatchSpec, SequenceSample


@dataclasses.dataclass
class GenerationHyperparameters:
    """Sampling configuration (mirrors reference GenerationHyperparameters)."""

    n: int = 1  # group size: samples per prompt
    max_new_tokens: int = 256
    min_new_tokens: int = 0
    greedy: bool = False
    top_p: float = 1.0
    top_k: int = -1
    temperature: float = 1.0
    stop_token_ids: List[int] = dataclasses.field(default_factory=list)

    def new(self, **kwargs) -> "GenerationHyperparameters":
        d = dataclasses.asdict(self)
        d.update(kwargs)
        return GenerationHyperparameters(**d)


@dataclasses.dataclass
class FinetuneSpec:
    total_train_epochs: int = 1
    dataset_size: int = 0
    train_batch_size: int = 1

    @property
    def steps_per_epoch(self) -> int:
        return max(1, self.dataset_size // max(1, self.train_batch_size))

    @property
    def total_train_steps(self) -> int:
        return self.total_train_epochs * self.steps_per_epoch


class TrainEngine(abc.ABC):
    """What algorithm interfaces call. All data is packed SequenceSamples.

    Implementations: `areal_tpu.engine.jax_engine.JaxTrainEngine` (optax
    train + inference + in-framework generation) and the mock engine for
    CPU system tests.
    """

    @abc.abstractmethod
    def train_batch(
        self,
        input_: SequenceSample,
        mb_spec: MicroBatchSpec,
        loss_fn: Any,
        loss_weight_fn: Any,
        token_normalize_scope: str = "global",
        version_steps: Optional[int] = None,
        loss_name: str = "loss",
    ) -> Dict[str, float]:
        """Run forward+backward+update over micro-batches; returns host
        stats. `version_steps` positions the LR schedule (None = the
        engine's own step count); see JaxTrainEngine.train_batch."""

    @abc.abstractmethod
    def forward(
        self,
        input_: SequenceSample,
        mb_spec: MicroBatchSpec,
        output_key: str = "logprobs",
        post_hook: Optional[Callable] = None,
    ) -> Optional[SequenceSample]:
        """Gradient-free forward over micro-batches, gathered to host."""

    @abc.abstractmethod
    def generate(
        self,
        input_: SequenceSample,
        mb_spec: MicroBatchSpec,
        tokenizer: Any,
        gconfig: GenerationHyperparameters,
    ) -> Optional[SequenceSample]:
        """In-framework generation (sync PPO path)."""

    def eval(self):
        return self

    def train(self, mode: bool = True):
        return self


@dataclasses.dataclass
class Model:
    """A named model hosted by a model worker: engine + tokenizer + version."""

    name: ModelName
    module: Optional[TrainEngine]
    tokenizer: Any
    version: int = 0
    ft_spec: FinetuneSpec = dataclasses.field(default_factory=FinetuneSpec)

    def inc_version(self):
        self.version += 1


class ModelInterface(abc.ABC):
    """Algorithm glue executed by MFCs (ppo_actor, ppo_critic, sft, reward...).

    Mirrors reference ModelInterface (realhf/api/core/model_api.py:759).
    """

    def save(self, model: Model, save_dir: str):
        pass

    def evaluate(self, model: Model, eval_dataloader) -> Dict:
        return {}

    def inference(
        self, model: Model, input_: SequenceSample, mb_spec: MicroBatchSpec
    ) -> Optional[SequenceSample]:
        raise NotImplementedError()

    def generate(
        self, model: Model, input_: SequenceSample, mb_spec: MicroBatchSpec
    ) -> Optional[SequenceSample]:
        raise NotImplementedError()

    def train_step(
        self, model: Model, input_: SequenceSample, mb_spec: MicroBatchSpec
    ) -> Dict | List[Dict]:
        raise NotImplementedError()


class ModelBackend(abc.ABC):
    """Wraps a bare Model with an engine (optimizer state etc.)."""

    @abc.abstractmethod
    def initialize(self, model: Model, spec: FinetuneSpec) -> Model:
        ...

    def save(self, model: Model, save_dir: str):
        pass

    def load(self, model: Model, load_dir: str):
        pass


# ---------------------------------------------------------------------------
# Generation server API types (reference: model_api.py:46-205)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class GenReqMeta:
    """What the gserver manager needs to route a request."""

    prompt_len: int = 0
    group_size: int = 1
    new_token_budget: int = 0
    predicted_new_tokens: Optional[int] = None
    previous_server_url: str = ""
    previous_version: int = -1


@dataclasses.dataclass
class APIGenerateInput:
    qid: str
    prompt_ids: List[int]
    input_ids: List[int]  # prompt + previously generated (resubmission prefix)
    gconfig: GenerationHyperparameters
    stop_token_ids: List[int] = dataclasses.field(default_factory=list)
    return_logprob: bool = True
    version_start: int = -1
    metadata: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class APIGenerateOutput:
    qid: str
    prompt_ids: List[int] = dataclasses.field(default_factory=list)
    input_ids: List[int] = dataclasses.field(default_factory=list)
    output_ids: List[int] = dataclasses.field(default_factory=list)
    output_logprobs: List[float] = dataclasses.field(default_factory=list)
    no_eos: bool = True  # True if generation stopped for a non-EOS reason
    version_start: int = -1
    version_end: int = -1
    latency: float = 0.0
    # Tokens resubmitted for prefill after interrupts/chunk boundaries —
    # the measured cost of interruptible generation (tracing + telemetry).
    reprefill_tokens: int = 0
    n_interruptions: int = 0

    @classmethod
    def from_input(cls, inp: APIGenerateInput) -> "APIGenerateOutput":
        return cls(
            qid=inp.qid,
            prompt_ids=list(inp.prompt_ids),
            input_ids=list(inp.input_ids),
            version_start=inp.version_start,
        )

    @property
    def gen_len(self) -> int:
        return len(self.output_ids)


@dataclasses.dataclass
class BundledGenerationOutputs:
    """A prompt group's finished generations, handed to the agent/trainer."""

    qid: str
    prompt_ids: List[int]
    seqs: List[List[int]]  # prompt + answer, per group member
    logprobs: List[List[float]]  # aligned with seqs (prompt positions = 0)
    no_eos: List[bool]
    version_start: List[int]
    version_end: List[int]
    reprefill_tokens: List[int] = dataclasses.field(default_factory=list)
    n_interruptions: List[int] = dataclasses.field(default_factory=list)

    @classmethod
    def from_api_outputs(
        cls, outputs: List[APIGenerateOutput]
    ) -> "BundledGenerationOutputs":
        assert len({o.qid for o in outputs}) == 1
        prompt = outputs[0].prompt_ids
        return cls(
            qid=outputs[0].qid,
            prompt_ids=list(prompt),
            seqs=[list(o.prompt_ids) + list(o.output_ids) for o in outputs],
            logprobs=[[0.0] * len(o.prompt_ids) + list(o.output_logprobs) for o in outputs],
            no_eos=[o.no_eos for o in outputs],
            version_start=[o.version_start for o in outputs],
            version_end=[o.version_end for o in outputs],
            reprefill_tokens=[o.reprefill_tokens for o in outputs],
            n_interruptions=[o.n_interruptions for o in outputs],
        )

    @property
    def prompt_len(self) -> int:
        return len(self.prompt_ids)


# ---------------------------------------------------------------------------
# Registries
# ---------------------------------------------------------------------------

MODEL_REGISTRY = Registry("model")
INTERFACE_REGISTRY = Registry("interface")
BACKEND_REGISTRY = Registry("backend")


def register_model(name: str, factory):
    MODEL_REGISTRY.register(name, factory)


def make_model(cfg: ModelAbstraction | str, **kwargs) -> Model:
    return MODEL_REGISTRY.make(cfg, **kwargs)


def register_interface(name: str, factory):
    INTERFACE_REGISTRY.register(name, factory)


def make_interface(cfg: ModelInterfaceAbstraction | str, **kwargs) -> ModelInterface:
    return INTERFACE_REGISTRY.make(cfg, **kwargs)


def register_backend(name: str, factory):
    BACKEND_REGISTRY.register(name, factory)


def make_backend(cfg: ModelBackendAbstraction | str, **kwargs) -> ModelBackend:
    return BACKEND_REGISTRY.make(cfg, **kwargs)


# HF model family registry: family name -> conversion helpers, filled by
# areal_tpu.models.hf.
HF_FAMILY_REGISTRY: Dict[str, Any] = {}


def register_hf_family(name: str, helpers: Any):
    if name in HF_FAMILY_REGISTRY:
        raise ValueError(f"HF family {name!r} already registered")
    HF_FAMILY_REGISTRY[name] = helpers

"""Paired pos/neg answer dataset for reward modelling
(reference impl/dataset/rw_paired_dataset.py).

jsonl rows need "prompt", "pos_answers", "neg_answers" (equal-length lists).
Each item packs up to `max_pairs_per_prompt` (pos, neg) sequence pairs:
`packed_input_ids` holds the 2*group_size sequences back to back (each
prompt+answer), `group_factor` weighs the pairwise loss, `prompt_lens`
records the shared prompt length.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

from areal_tpu.api import data_api
from areal_tpu.base import logging

logger = logging.getLogger("rw_paired_dataset")


class RewardModelingPairedDataset:
    def __init__(
        self,
        util: data_api.DatasetUtility,
        max_length: int,
        max_pairs_per_prompt: int = 2,
        dataset_path: Optional[str] = None,
        dataset_builder: Optional[Callable[[], List[Dict]]] = None,
    ):
        self.util = util
        tok = util.tokenizer
        data = data_api.load_shuffle_split_dataset(util, dataset_path, dataset_builder)
        self.max_pairs_per_prompt = max_pairs_per_prompt
        self.ids = [str(x["id"]) for x in data]
        # Pair selection must be a pure function of (seed, dp_rank, idx):
        # a shared stateful RNG would make re-reads and checkpoint-recovery
        # replays return different pairs.
        self._seed_base = (util.seed * 1_000_003 + util.dp_rank) % (2**31 - 1)

        eos = tok.eos_token or ""
        self.prompt_lens: List[int] = []
        self.pos_tokens: List[List[List[int]]] = []
        self.neg_tokens: List[List[List[int]]] = []
        for x in data:
            assert len(x["pos_answers"]) == len(x["neg_answers"]) > 0, x["id"]
            ptoks = tok(x["prompt"], truncation=True, max_length=max_length)["input_ids"]
            self.prompt_lens.append(len(ptoks))
            enc = lambda ans: tok(
                x["prompt"] + ans + eos, truncation=True, max_length=max_length
            )["input_ids"]
            self.pos_tokens.append([enc(a) for a in x["pos_answers"]])
            self.neg_tokens.append([enc(a) for a in x["neg_answers"]])
        logger.info(f"RewardModelingPairedDataset: {len(self.ids)} prompts")

    def __len__(self):
        return len(self.ids)

    def __getitem__(self, idx: int) -> data_api.SequenceSample:
        n_pairs = len(self.pos_tokens[idx])
        group_size = min(self.max_pairs_per_prompt, n_pairs)
        rng = np.random.RandomState((self._seed_base + idx * 9973) % (2**31 - 1))
        pair_idx = rng.choice(n_pairs, group_size, replace=False)

        seqs: List[int] = []
        input_lens: List[int] = []
        for i in pair_idx:
            for toks in (self.pos_tokens[idx][i], self.neg_tokens[idx][i]):
                seqs.extend(toks)
                input_lens.append(len(toks))

        return data_api.SequenceSample(
            ids=[self.ids[idx]],
            keys={"packed_input_ids", "group_factor", "prompt_lens"},
            data=dict(
                packed_input_ids=np.asarray(seqs, dtype=np.int32),
                group_factor=np.full((1,), 1.0 / group_size, dtype=np.float32),
                prompt_lens=np.asarray([self.prompt_lens[idx]], dtype=np.int32),
            ),
            seqlens={
                "packed_input_ids": [input_lens],
                "group_factor": [[1]],
                "prompt_lens": [[1]],
            },
        )


data_api.register_dataset("rw_pair", RewardModelingPairedDataset)

"""areal-lint: repo-specific AST static analysis (stdlib ``ast`` only).

Eight checkers over the contracts the system already relies on but no
generic tool enforces. Single-process (PR 10):

- ``loop-only`` — engine-loop thread discipline (serving.py state that
  has no locks *by design* may only be touched from the loop call
  graph or through the ``_run_on_loop`` door);
- ``blocking-async`` — no blocking work on an asyncio event loop
  (``time.sleep``, sync HTTP, file I/O, subprocess, jax device ops
  inside ``async def`` unless pushed to an executor);
- ``env-knob`` — every ``AREAL_*`` env read goes through
  ``areal_tpu.base.env_registry`` and every registry entry is alive;
- ``wire-schema`` — ``areal-*/vN`` schema strings come from
  ``areal_tpu.base.wire_schemas`` only.

Cross-process (PR 13), each backed by a declared registry so the
contract is machine-readable:

- ``wire-contract`` — every HTTP route, client path, and deliberate
  status code pairs against ``areal_tpu.base.wire_routes``;
- ``metrics-registry`` — every ``areal:*`` /metrics line and
  ``perf/*`` stats scalar key is declared in
  ``areal_tpu.base.metrics_registry``; parse sites use its constants;
- ``chaos-registry`` — every fault-injection point and
  ``AREAL_FAULTS`` spec names a point declared in
  ``areal_tpu.base.fault_points``;
- ``lock-order`` — sync-lock deadlock classes: await-under-lock,
  loop-door-under-lock, AB/BA acquisition cycles.

CLI: ``python scripts/areal_lint.py [paths...]``. Gate: tier-1 tests
run the linter over ``areal_tpu/`` (all checkers + generated-docs
drift) and over ``tests/``+``scripts/`` (the cross-process client
side) and fail on any unallowlisted finding. See
docs/static_analysis.md.

This package must import neither jax nor anything that does: the gate
asserts ``jax`` stays out of ``sys.modules``.
"""

from areal_tpu.lint.common import Finding, LintConfigError  # noqa: F401
from areal_tpu.lint.runner import LintConfig, run_lint  # noqa: F401

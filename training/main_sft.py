"""SFT entry point (reference training/main_sft.py).

Usage:
    python training/main_sft.py \
        experiment_name=my-sft model.path=/ckpts/qwen2.5-1.5b \
        dataset.path=/data/sft.jsonl train_batch_size=64
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from areal_tpu.api.cli_args import SFTExpConfig
from training.utils import main

if __name__ == "__main__":
    if any(a.startswith("n_hosts=") for a in sys.argv[1:]):
        # Pod-scale path: one SPMD process per host over a global mesh
        # (training/multihost.py) instead of the single-host controller.
        from training.multihost import _HOST_ENV, _parse_argv, host_main, launch_multihost

        meta, cfg, overrides = _parse_argv(sys.argv[1:])
        rank_env = os.environ.get(_HOST_ENV)
        if rank_env is None:
            launch_multihost(
                meta["n_hosts"], overrides, meta["mesh_spec"],
                meta["steps"], meta["out"],
            )
        else:
            host_main(cfg, int(rank_env), meta["n_hosts"],
                      meta["mesh_spec"], meta["steps"], meta["out"])
    else:
        main("sft", SFTExpConfig)

"""name_resolve backend tests (mirrors reference tests/distributed/test_name_resolve.py)."""

import threading
import time

import pytest

from areal_tpu.base import name_resolve
from areal_tpu.base.name_resolve import (
    MemoryNameRecordRepository,
    NameEntryExistsError,
    NameEntryNotFoundError,
    NfsNameRecordRepository,
)


@pytest.fixture(scope="module")
def kv_server():
    from areal_tpu.base.name_resolve_kv import KvStoreServer

    srv = KvStoreServer("127.0.0.1", 0).start()
    yield srv
    srv.stop()


@pytest.fixture(params=["memory", "nfs", "kv"])
def repo(request, tmp_path, kv_server):
    if request.param == "memory":
        r = MemoryNameRecordRepository()
    elif request.param == "kv":
        from areal_tpu.base.name_resolve_kv import KvNameRecordRepository

        r = KvNameRecordRepository(kv_server.address.replace("0.0.0.0", "127.0.0.1"))
    else:
        r = NfsNameRecordRepository(record_root=str(tmp_path / "nr"))
    yield r
    r.reset()


def test_add_get_delete(repo):
    repo.add("a/b/c", "v1")
    assert repo.get("a/b/c") == "v1"
    with pytest.raises(NameEntryExistsError):
        repo.add("a/b/c", "v2")
    repo.add("a/b/c", "v2", replace=True)
    assert repo.get("a/b/c") == "v2"
    repo.delete("a/b/c")
    with pytest.raises(NameEntryNotFoundError):
        repo.get("a/b/c")
    with pytest.raises(NameEntryNotFoundError):
        repo.delete("a/b/c")


def test_subtree(repo):
    repo.add("root/x/1", "a")
    repo.add("root/x/2", "b")
    repo.add("root/y", "c")
    assert repo.get_subtree("root/x") == ["a", "b"]
    assert len(repo.find_subtree("root")) == 3
    repo.clear_subtree("root/x")
    assert repo.get_subtree("root/x") == []
    assert repo.get("root/y") == "c"


def test_add_subentry(repo):
    k1 = repo.add_subentry("servers", "url1")
    k2 = repo.add_subentry("servers", "url2")
    assert k1 != k2
    assert sorted(repo.get_subtree("servers")) == ["url1", "url2"]


def test_wait(repo):
    def _later():
        time.sleep(0.2)
        repo.add("late/key", "done")

    t = threading.Thread(target=_later)
    t.start()
    assert repo.wait("late/key", timeout=5) == "done"
    t.join()
    with pytest.raises(TimeoutError):
        repo.wait("never/key", timeout=0.2)


def test_module_facade(tmp_path):
    name_resolve.reconfigure("nfs", record_root=str(tmp_path / "nr2"))
    name_resolve.add("k", "v")
    assert name_resolve.get("k") == "v"
    name_resolve.reset()


def test_nfs_cross_instance(tmp_path):
    # Two repo instances over the same root see each other's records.
    r1 = NfsNameRecordRepository(record_root=str(tmp_path / "shared"))
    r2 = NfsNameRecordRepository(record_root=str(tmp_path / "shared"))
    r1.add("peer/0", "addr0")
    assert r2.get("peer/0") == "addr0"
    r1.reset()


def test_kv_lease_expiry_and_keepalive(kv_server):
    """etcd lease semantics: a TTL key vanishes when its owner stops
    refreshing (here: owner repo closed), but survives while the owner's
    keepalive loop runs."""
    from areal_tpu.base.name_resolve_kv import KvNameRecordRepository

    addr = kv_server.address.replace("0.0.0.0", "127.0.0.1")
    owner = KvNameRecordRepository(addr)
    reader = KvNameRecordRepository(addr)
    owner.add("lease/worker0", "alive", keepalive_ttl=0.3)
    # Lease held: survives well past 3*ttl thanks to the keepalive loop.
    time.sleep(1.2)
    assert reader.get("lease/worker0") == "alive"
    # Owner dies (stops refreshing without deleting): key expires.
    owner._stop.set()
    owner._close_socket()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        try:
            reader.get("lease/worker0")
            time.sleep(0.1)
        except NameEntryNotFoundError:
            break
    else:
        raise AssertionError("leased key never expired after owner death")
    reader.reset()


def test_kv_reconnect(kv_server):
    """Client transparently reconnects after a dropped connection."""
    from areal_tpu.base.name_resolve_kv import KvNameRecordRepository

    addr = kv_server.address.replace("0.0.0.0", "127.0.0.1")
    r = KvNameRecordRepository(addr)
    r.add("rc/a", "1")
    r._close_socket()  # simulate a network drop
    assert r.get("rc/a") == "1"
    r.reset()


def test_kv_module_facade(kv_server):
    addr = kv_server.address.replace("0.0.0.0", "127.0.0.1")
    repo = name_resolve.reconfigure("kv", address=addr)
    name_resolve.add("facade/k", "v")
    assert name_resolve.get("facade/k") == "v"
    repo.reset()
    name_resolve.reconfigure("nfs")

"""Content-addressed chunking for the weight-distribution plane.

The raw-bin dump format (system/weight_transfer.py) is one contiguous
byte blob per version. The distribution plane (system/weight_plane.py)
moves that blob over HTTP in fixed-size chunks; every chunk is named by
its content hash so a receiver can verify each piece independently,
resume a torn connection mid-chunk, and safely accept bytes from ANY
holder (trainer origin or a sibling generation server) — the hash, not
the peer, is the authority.

Kept in ``base`` (stdlib-only, no jax/numpy) so the trainer-side source,
the engine-side fetch client, and the bench workload all share one
definition of "a chunk".
"""

from __future__ import annotations

import hashlib
import os
from typing import Dict, List, Optional, Tuple

from areal_tpu.base.wire_schemas import WEIGHT_CHUNKS_V1 as CHUNK_SCHEMA

# 8 MiB default: large enough that per-chunk HTTP overhead is noise for
# GB-scale payloads, small enough that a resumed transfer re-pays at
# most one chunk and a fanout tree pipelines across peers quickly.
DEFAULT_CHUNK_BYTES = 8 << 20


def hash_chunk(data) -> str:
    """Content hash of one chunk (sha256; full hex so a collision-forged
    chunk is out of reach for anything short of breaking sha256)."""
    return hashlib.sha256(bytes(data)).hexdigest()


def chunk_spans(total_bytes: int, chunk_bytes: int) -> List[Tuple[int, int]]:
    """[(offset, length), ...] covering [0, total_bytes). The final chunk
    is short; a zero-byte payload has zero chunks."""
    if chunk_bytes <= 0:
        raise ValueError(f"chunk_bytes must be > 0, got {chunk_bytes}")
    return [
        (off, min(chunk_bytes, total_bytes - off))
        for off in range(0, total_bytes, chunk_bytes)
    ]


def build_chunk_index(bin_path: str, chunk_bytes: int = DEFAULT_CHUNK_BYTES) -> Dict:
    """Stream the bin once and return its chunk index:

    ``{schema, chunk_bytes, total_bytes, n_chunks, hashes: [hex, ...]}``

    Raises OSError if the bin vanishes mid-read (GC race — the caller
    retries against the refreshed manifest, weight_transfer.py).
    """
    total = os.path.getsize(bin_path)
    hashes: List[str] = []
    with open(bin_path, "rb") as f:
        for _, length in chunk_spans(total, chunk_bytes):
            data = f.read(length)
            if len(data) != length:
                raise OSError(
                    f"short read on {bin_path}: wanted {length}, "
                    f"got {len(data)} (torn write or concurrent GC)"
                )
            hashes.append(hash_chunk(data))
    return {
        "schema": CHUNK_SCHEMA,
        "chunk_bytes": int(chunk_bytes),
        "total_bytes": int(total),
        "n_chunks": len(hashes),
        "hashes": hashes,
    }


class StreamChunker:
    """Incrementally hash a byte stream into the same chunk index
    ``build_chunk_index`` produces, without materializing the stream.

    The dump path (system/weight_transfer.dump_raw_params) feeds each
    leaf's bytes through this while writing the bin, then publishes the
    index as a sidecar — so the weight-plane origin never has to re-read
    and re-hash a multi-GB bin it just wrote."""

    def __init__(self, chunk_bytes: int = DEFAULT_CHUNK_BYTES):
        if chunk_bytes <= 0:
            raise ValueError(f"chunk_bytes must be > 0, got {chunk_bytes}")
        self.chunk_bytes = int(chunk_bytes)
        self.total = 0
        self.hashes: List[str] = []
        self._h = hashlib.sha256()
        self._fill = 0  # bytes fed into the current (open) chunk

    def update(self, data) -> None:
        mv = memoryview(data).cast("B")
        while len(mv):
            take = min(len(mv), self.chunk_bytes - self._fill)
            self._h.update(mv[:take])
            self._fill += take
            self.total += take
            if self._fill == self.chunk_bytes:
                self.hashes.append(self._h.hexdigest())
                self._h = hashlib.sha256()
                self._fill = 0
            mv = mv[take:]

    def finish(self) -> Dict:
        if self._fill:
            self.hashes.append(self._h.hexdigest())
            self._h = hashlib.sha256()
            self._fill = 0
        return {
            "schema": CHUNK_SCHEMA,
            "chunk_bytes": self.chunk_bytes,
            "total_bytes": int(self.total),
            "n_chunks": len(self.hashes),
            "hashes": list(self.hashes),
        }


def verify_chunk(data, expected_hash: str) -> bool:
    return hash_chunk(data) == expected_hash


# ----------------------------------------------------------------------
# Slice -> byte-range resolution (the shard-aware manifest layer)
#
# A generation server that holds only one tensor-parallel shard of the
# model should fetch only that shard's bytes. The per-leaf layout
# (path -> shape/offset/nbytes in the raw bin, published by
# system/weight_transfer.dump_raw_params) plus a per-dimension slice
# tuple (derived from parallel/sharding.py partition specs by the
# caller — this module stays jax-free) resolves to the minimal covering
# set of byte ranges in the bin; the weight-plane origin concatenates
# those ranges into a per-shard chunk stream with its own content
# hashes, so sliced transfer keeps the full hash-authority discipline.
# ----------------------------------------------------------------------


def slice_byte_ranges(
    offset: int, shape, itemsize: int, slices
) -> List[Tuple[int, int]]:
    """Minimal covering [(byte_off, length), ...] for a row-major slab.

    ``slices`` is one ``(start, stop)`` per dimension (``len(shape)``
    entries). Contiguous runs are maximized: trailing dimensions that
    are fully covered fold into a single run per outer index, and
    adjacent runs merge. A scalar (empty shape) is one full-leaf range.
    """
    shape = list(shape)
    slices = [(int(a), int(b)) for a, b in slices]
    if len(slices) != len(shape):
        raise ValueError(
            f"{len(slices)} slices for a rank-{len(shape)} leaf"
        )
    for (a, b), dim in zip(slices, shape):
        if not (0 <= a <= b <= dim):
            raise ValueError(f"slice ({a}, {b}) out of bounds for dim {dim}")
        if b == a:
            return []  # empty slice: nothing to fetch
    if not shape:
        return [(offset, itemsize)]
    # Innermost dim k whose suffix (k+1..) is fully covered: everything
    # from k inward is one contiguous run per outer index combination.
    k = len(shape) - 1
    while k > 0 and all(
        s == (0, d) for s, d in zip(slices[k:], shape[k:])
    ):
        k -= 1
    inner = itemsize
    for d in shape[k + 1:]:
        inner *= d
    run_len = (slices[k][1] - slices[k][0]) * inner
    # Strides (in bytes) of dims 0..k-1.
    strides = []
    s = inner * shape[k]
    for d in reversed(shape[:k]):
        strides.append(s)
        s *= d
    strides.reverse()
    ranges: List[Tuple[int, int]] = []

    def emit(dim_idx: int, base: int):
        if dim_idx == k:
            start = base + slices[k][0] * inner
            if ranges and ranges[-1][0] + ranges[-1][1] == start:
                ranges[-1] = (ranges[-1][0], ranges[-1][1] + run_len)
            else:
                ranges.append((start, run_len))
            return
        a, b = slices[dim_idx]
        for i in range(a, b):
            emit(dim_idx + 1, base + i * strides[dim_idx])

    emit(0, offset)
    return ranges


def shard_stream_plan(segments: List[Dict]) -> Dict:
    """Plan a shard's virtual payload from sliced layout segments.

    Each segment describes one sliced slab of the source bin:
    ``{"offset", "shape", "nbytes", "slices", ...}`` (``nbytes`` is the
    FULL slab's size, from which the itemsize is derived; extra keys
    pass through). Returns::

        {"segments": [...],   # inputs + local_offset/local_nbytes/local_shape
         "ranges": [...],     # (bin_off, len) gather list, stream order
         "total_bytes": int}

    The shard stream is the concatenation of every segment's covering
    ranges in segment order — the origin serves chunks of this stream by
    gathering the ranges; the client's local buffer holds each segment's
    sliced slab contiguously at ``local_offset`` with ``local_shape``.
    """
    out_segments: List[Dict] = []
    ranges: List[Tuple[int, int]] = []
    cursor = 0
    for seg in segments:
        shape = list(seg["shape"])
        n_items = 1
        for d in shape:
            n_items *= d
        if n_items <= 0:
            raise ValueError(f"empty-shape segment: {seg}")
        itemsize = int(seg["nbytes"]) // n_items
        if itemsize * n_items != int(seg["nbytes"]):
            raise ValueError(
                f"nbytes {seg['nbytes']} not divisible by {n_items} items"
            )
        slc = list(seg["slices"])
        seg_ranges = slice_byte_ranges(
            int(seg["offset"]), shape, itemsize, slc
        )
        local_shape = [b - a for a, b in slc]
        local_nbytes = itemsize
        for d in local_shape:
            local_nbytes *= d
        assert sum(r[1] for r in seg_ranges) == local_nbytes
        entry = dict(seg)
        entry["local_shape"] = local_shape
        entry["local_offset"] = cursor
        entry["local_nbytes"] = local_nbytes
        out_segments.append(entry)
        # Merge ranges only WITHIN the stream order (ranges must stay in
        # stream order so offset->range lookup is a prefix sum).
        for r in seg_ranges:
            if ranges and ranges[-1][0] + ranges[-1][1] == r[0]:
                ranges[-1] = (ranges[-1][0], ranges[-1][1] + r[1])
            else:
                ranges.append(r)
        cursor += local_nbytes
    return {
        "segments": out_segments,
        "ranges": ranges,
        "total_bytes": cursor,
    }


def stream_prefix(ranges: List[Tuple[int, int]]) -> List[int]:
    """Cumulative stream offset at which each range begins (plus the
    total as a final sentinel). Built once per cached shard plan so
    ``gather_stream`` can bisect instead of scanning — a fine-grained
    slicing (one range per outer index of a last-dim-sharded leaf) can
    produce 1e5+ ranges, and the origin serves one window per chunk."""
    pre = [0]
    for _, ln in ranges:
        pre.append(pre[-1] + ln)
    return pre


def gather_stream(
    read_at, ranges: List[Tuple[int, int]], start: int, length: int,
    prefix: Optional[List[int]] = None,
) -> bytes:
    """Read ``[start, start+length)`` of the virtual stream defined by
    ``ranges`` via ``read_at(bin_offset, n) -> bytes`` (the origin's
    pread). ``prefix`` (see :func:`stream_prefix`) makes the first-range
    lookup O(log n); without it the scan starts at range 0. Raises
    OSError on short reads (GC race; caller 404s)."""
    import bisect

    out = []
    need = length
    if prefix is not None:
        i = max(0, bisect.bisect_right(prefix, start) - 1)
        pos = prefix[i]
    else:
        i, pos = 0, 0
    for off, ln in ranges[i:]:
        if need <= 0:
            break
        if start < pos + ln:
            lo = max(0, start - pos)
            take = min(ln - lo, need)
            data = read_at(off + lo, take)
            if len(data) != take:
                raise OSError(
                    f"short stream read: wanted {take}, got {len(data)}"
                )
            out.append(data)
            need -= take
        pos += ln
    if need > 0:
        raise ValueError(
            f"stream read past end: [{start}, {start + length}) of {pos}"
        )
    return b"".join(out)

"""qid-keyed prefix KV reuse (the radix-cache role of the reference's
serving backend): a resubmission whose prompt extends a parked sequence
prefills only the delta. Partial rollouts resubmit prompt+generated with
one qid per sample (system/partial_rollout.py:88), so this removes the
whole-prefix recompute from every chunk boundary."""

import threading

import jax
import numpy as np
import pytest

from areal_tpu.engine.serving import GenRequest, ServingEngine
from areal_tpu.models.config import TransformerConfig
from areal_tpu.models.transformer import init_params


def small_cfg():
    return TransformerConfig(
        n_layers=2,
        hidden_dim=64,
        n_q_heads=4,
        n_kv_heads=2,
        head_dim=16,
        intermediate_dim=128,
        vocab_size=256,
        max_position_embeddings=512,
        compute_dtype="float32",
    )


@pytest.fixture(scope="module")
def model():
    cfg = small_cfg()
    return cfg, init_params(cfg, jax.random.PRNGKey(0))


def _engine(cfg, params, prefix_cache_tokens, **kw):
    eng = ServingEngine(
        cfg,
        params,
        max_batch_size=4,
        max_seq_len=256,
        decode_block_steps=4,
        prompt_bucket=16,
        eos_token_id=None,
        page_size=16,
        prefix_cache_tokens=prefix_cache_tokens,
        **kw,
    )
    eng.start()
    return eng


def _gen(eng, qid, ids, max_new):
    done = threading.Event()
    holder = {}

    def cb(res):
        holder["res"] = res
        done.set()

    eng.submit(
        GenRequest(
            qid=qid,
            input_ids=list(ids),
            max_new_tokens=max_new,
            greedy=True,
            done_cb=cb,
        )
    )
    assert done.wait(300)
    return holder["res"]


def test_resubmission_reuses_prefix_and_matches_uncached(model):
    """Chunked generation through resubmission (the partial-rollout
    pattern) hits the cache and produces exactly the tokens an
    uninterrupted run would."""
    cfg, params = model
    rng = np.random.RandomState(1)
    prompt = rng.randint(0, cfg.vocab_size, size=40).tolist()

    ref_eng = _engine(cfg, params, prefix_cache_tokens=None)
    try:
        full = _gen(ref_eng, "ref", prompt, max_new=16).output_ids
    finally:
        ref_eng.stop()

    eng = _engine(cfg, params, prefix_cache_tokens=4096)
    try:
        out1 = _gen(eng, "s/0", prompt, max_new=8).output_ids
        assert eng.prefix_cache_hits == 0
        out2 = _gen(eng, "s/0", prompt + out1, max_new=8).output_ids
        assert eng.prefix_cache_hits == 1
        # Reused at least the pages-aligned part of prompt + out1.
        assert eng.prefix_tokens_reused >= len(prompt)
        assert out1 + out2 == full
    finally:
        eng.stop()


def test_cache_disabled_frees_pages(model):
    cfg, params = model
    eng = _engine(cfg, params, prefix_cache_tokens=None)
    try:
        free0 = eng._allocator.n_free
        _gen(eng, "a", list(range(30)), max_new=4)
        assert eng._allocator.n_free == free0  # everything returned
        assert eng._cached_tokens == 0
    finally:
        eng.stop()


def test_budget_eviction_lru(model):
    cfg, params = model
    eng = _engine(cfg, params, prefix_cache_tokens=64)
    try:
        free0 = eng._allocator.n_free + 0
        _gen(eng, "a", list(range(40)), max_new=4)  # ~44 tokens cached
        assert "a" in eng._prefix_cache
        _gen(eng, "b", list(range(40, 80)), max_new=4)
        # 2 x ~44 > 64: the older entry was evicted.
        assert "a" not in eng._prefix_cache and "b" in eng._prefix_cache
        eng._flush_prefix_cache()
        assert eng._cached_tokens == 0
        assert eng._allocator.n_free == free0  # no page leaked
    finally:
        eng.stop()


def test_weight_update_flushes_cache(model):
    cfg, params = model
    eng = _engine(cfg, params, prefix_cache_tokens=4096)
    try:
        prompt = list(range(30))
        out1 = _gen(eng, "w", prompt, max_new=4).output_ids
        assert eng._cached_tokens > 0
        eng.update_params(
            jax.tree_util.tree_map(np.asarray, params), allow_interrupt=True
        )
        _gen(eng, "warm", [1, 2, 3], max_new=2)  # lets the swap land
        assert eng._cached_tokens == 0  # old-weight KV flushed
        out2 = _gen(eng, "w", prompt + out1, max_new=4).output_ids
        assert eng.prefix_cache_hits == 0  # no stale reuse
        assert len(out2) == 4
    finally:
        eng.stop()


def test_pool_pressure_evicts_cache_before_preempting(model):
    """Speculative cache pages yield to real admissions: a request that
    needs more pages than are free succeeds by evicting the cache."""
    cfg, params = model
    # Pool of 12 usable pages (16 tokens each).
    eng = _engine(
        cfg, params, prefix_cache_tokens=100000, kv_pool_tokens=12 * 16
    )
    try:
        _gen(eng, "old", list(range(80)), max_new=8)  # caches ~6 pages
        assert eng._cached_tokens > 0
        res = _gen(eng, "new", list(range(100, 200)), max_new=8)
        assert len(res.output_ids) == 8
        assert eng.n_preempted == 0  # served by eviction, not preemption
    finally:
        eng.stop()


def test_eviction_under_page_pressure_keeps_accounting_consistent(model):
    """ISSUE 6 satellite: drive the engine until `_evict_one_prefix`
    fires from ALLOCATION pressure (budget is effectively unlimited),
    then assert subsequent behavior is correct: the evicted qid misses,
    a surviving qid hits and produces exactly the tokens an uncached
    continuation would, and the hit-rate accounting (hits /
    total_requests, cached-token sum, page conservation) stays
    consistent throughout."""
    cfg, params = model
    # 12 usable pages of 16 tokens; budget never binds.
    eng = _engine(
        cfg, params, prefix_cache_tokens=100000, kv_pool_tokens=12 * 16
    )
    try:
        free_total = eng._allocator.n_free

        def check_invariants():
            cached_pages = sum(
                len(p) for _, p in eng._prefix_cache.values()
            )
            slot_pages = sum(len(p) for p in eng._slot_pages)
            assert eng._allocator.n_free + cached_pages + slot_pages == (
                free_total
            )
            assert eng._cached_tokens == sum(
                len(t) for t, _ in eng._prefix_cache.values()
            )
            assert eng.prefix_cache_hits <= eng.total_requests

        out_a = _gen(eng, "a", list(range(40)), max_new=8).output_ids
        out_b = _gen(eng, "b", list(range(50, 90)), max_new=8).output_ids
        assert "a" in eng._prefix_cache and "b" in eng._prefix_cache
        check_invariants()

        # A fresh prompt needing more pages than are free forces the
        # LRU entry ("a") out; "b" must survive.
        _gen(eng, "c", list(range(100, 200)), max_new=8)
        assert "a" not in eng._prefix_cache, "pressure never evicted"
        assert "b" in eng._prefix_cache
        assert eng.n_preempted == 0  # served by eviction, not preemption
        check_invariants()

        # Surviving entry: the continuation hits and matches an
        # uncached continuation of the same sequence bit-for-bit.
        hits0 = eng.prefix_cache_hits
        out_b2 = _gen(eng, "b", list(range(50, 90)) + out_b, max_new=4)
        assert eng.prefix_cache_hits == hits0 + 1
        ref = _gen(eng, "bref", list(range(50, 90)) + out_b, max_new=4)
        assert out_b2.output_ids == ref.output_ids
        check_invariants()

        # Evicted entry: the same-qid resubmission is a MISS (no stale
        # reuse), still correct, and the accounting reflects it.
        hits1 = eng.prefix_cache_hits
        out_a2 = _gen(eng, "a", list(range(40)) + out_a, max_new=4)
        assert eng.prefix_cache_hits == hits1  # miss: entry was evicted
        assert len(out_a2.output_ids) == 4
        check_invariants()

        # Manual hit-rate cross-check against the counters the manager
        # aggregates fleet-wide (ratio of sums).
        assert eng.total_requests == 6
        assert eng.prefix_cache_hits == 1
        assert eng.prefix_cache_hits / eng.total_requests == (
            pytest.approx(1 / 6)
        )
    finally:
        eng.stop()


def test_first_token_finish_still_parks_prompt(model):
    """A request finishing at admission (budget 1) must still park its
    freshly prefilled prompt KV for a same-qid extension."""
    cfg, params = model
    eng = _engine(cfg, params, prefix_cache_tokens=4096)
    try:
        prompt = list(range(40))
        out1 = _gen(eng, "f/0", prompt, max_new=1).output_ids
        assert len(out1) == 1 and eng._cached_tokens >= len(prompt)
        out2 = _gen(eng, "f/0", prompt + out1, max_new=4).output_ids
        assert eng.prefix_cache_hits == 1
        assert len(out2) == 4
    finally:
        eng.stop()

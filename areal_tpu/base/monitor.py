"""Throughput accounting: analytic FLOP formulas and rollout statistics.

Counterpart of the reference's monitor module (realhf/base/monitor.py),
minus CUDA-specific kernel-trace parsing (the TPU analogue is
`jax.profiler` traces, handled in `areal_tpu.utils.profiling`). The FLOP
formulas are the standard dense-transformer counts used to report
TFLOP/s-per-chip.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence


@dataclasses.dataclass
class RolloutStat:
    """Counters the generation manager logs per interval."""

    submitted: int = 0
    accepted: int = 0
    running: int = 0
    gen_tokens: int = 0


def calculate_llama_forward_flops(
    batch_size: int,
    seqlens: Sequence[int],
    hidden_size: int,
    intermediate_size: int,
    vocab_size: int,
    n_layers: int,
    num_heads: int,
    num_kv_heads: int,
) -> int:
    """Forward FLOPs of a llama-family model over packed sequences.

    Matmul-only accounting (2*m*n*k per matmul), including the quadratic
    attention term computed per-sequence from `seqlens`.
    """
    total_tokens = int(sum(seqlens))
    head_dim = hidden_size // num_heads
    kv_size = head_dim * num_kv_heads
    # Projections: q (h->h), k/v (h->kv), o (h->h)
    attn_proj = 2 * total_tokens * hidden_size * (2 * hidden_size + 2 * kv_size)
    # Attention scores + values: 2 * sum(len^2) * h per each of QK^T and PV
    attn_quad = 4 * sum(int(l) ** 2 for l in seqlens) * hidden_size
    # Gated MLP: gate+up (h->i each), down (i->h)
    mlp = 2 * total_tokens * hidden_size * intermediate_size * 3
    # LM head
    head = 2 * total_tokens * hidden_size * vocab_size
    return n_layers * (attn_proj + attn_quad + mlp) + head


def calculate_llama_train_flops(*args, **kwargs) -> int:
    """Training = forward + backward ~= 3x forward."""
    return 3 * calculate_llama_forward_flops(*args, **kwargs)


def transformer_forward_flops(cfg, seqlens: Sequence[int]) -> int:
    """Forward FLOPs from an areal_tpu TransformerConfig over packed
    sequences (matmul-only, MoE-aware: only the top-k routed experts'
    FLOPs count per token).

    Unlike the llama formula above (API parity with the reference's
    hidden_size/num_heads signature, realhf/base/monitor.py:307), this
    uses the config's true q/kv/head dims, so GQA and decoupled head_dim
    models are counted exactly.
    """
    total_tokens = int(sum(seqlens))
    D = cfg.hidden_dim
    q_dim = cfg.n_q_heads * cfg.head_dim
    kv_dim = cfg.n_kv_heads * cfg.head_dim
    attn_proj = 2 * total_tokens * D * (2 * q_dim + 2 * kv_dim)
    attn_quad = 4 * sum(int(l) ** 2 for l in seqlens) * q_dim
    if cfg.moe is not None:
        e_dim = cfg.moe.expert_intermediate_dim or cfg.intermediate_dim
        mlp = 2 * total_tokens * D * (cfg.moe.top_k * e_dim) * 3
        mlp += 2 * total_tokens * D * cfg.moe.num_experts  # router
    else:
        n_in = 2 if cfg.mlp_type == "gated" else 1
        mlp = 2 * total_tokens * D * cfg.intermediate_dim * (n_in + 1)
    head = 2 * total_tokens * D * cfg.vocab_size
    return cfg.n_layers * (attn_proj + attn_quad + mlp) + head


def mfc_flops(
    cfg,
    interface_type: str,
    input_seqlens: Sequence[int],
    output_seqlens: Optional[Sequence[int]] = None,
) -> int:
    """Analytic FLOPs of one model function call, from the model's
    TransformerConfig and the packed batch shape (counterpart of the
    reference's FlopsCounter, realhf/system/flops_counter.py — computed
    worker-side here because the worker knows the true config+shapes).

    - train_step: 3x forward (fwd + bwd)
    - inference:  1x forward
    - generate:   prefill over prompts + per-token decode; approximated
      as one forward over the FULL (prompt+generated) sequences, which
      counts each decode step's matmuls once and the attention context
      quadratically — the same accounting the reference's gen formula
      reaches in closed form.
    """
    if interface_type == "train_step":
        return 3 * transformer_forward_flops(cfg, input_seqlens)
    if interface_type == "inference":
        return transformer_forward_flops(cfg, input_seqlens)
    if interface_type == "generate":
        full = output_seqlens if output_seqlens else input_seqlens
        return transformer_forward_flops(cfg, full)
    return 0


def calculate_llama_gen_flops(
    batch_size: int,
    prompt_lens: Sequence[int],
    gen_len: int,
    hidden_size: int,
    intermediate_size: int,
    vocab_size: int,
    n_layers: int,
    num_heads: int,
    num_kv_heads: int,
) -> int:
    """Generation FLOPs: one prefill over prompts plus `gen_len` decode steps."""
    flops = calculate_llama_forward_flops(
        batch_size,
        prompt_lens,
        hidden_size,
        intermediate_size,
        vocab_size,
        n_layers,
        num_heads,
        num_kv_heads,
    )
    head_dim = hidden_size // num_heads
    kv_size = head_dim * num_kv_heads
    # Closed form of sum_i sum_j (prompt_j + i) over decode steps i:
    # gen_len * sum(prompt) + B * gen_len*(gen_len-1)/2.
    total_ctx = gen_len * sum(int(l) for l in prompt_lens) + batch_size * (
        gen_len * (gen_len - 1) // 2
    )
    attn_proj = 2 * batch_size * hidden_size * (2 * hidden_size + 2 * kv_size)
    mlp = 2 * batch_size * hidden_size * intermediate_size * 3
    head = 2 * batch_size * hidden_size * vocab_size
    flops += gen_len * (n_layers * (attn_proj + mlp) + head)
    flops += n_layers * 4 * total_ctx * hidden_size
    return flops


# ---------------------------------------------------------------------------
# Device memory telemetry + OOM guard
# ---------------------------------------------------------------------------

# Fraction of HBM beyond which the worker self-terminates so the relaunch
# loop can recover it (reference REAL_GPU_MEMORY_KILL_THRESHOLD,
# realhf/system/model_worker.py:1507-1610).
MEMORY_KILL_THRESHOLD_ENV = "AREAL_TPU_MEMORY_KILL_THRESHOLD"


class DeviceOOMGuardError(RuntimeError):
    """Raised when device memory use crosses the kill threshold."""


def device_memory_stats(devices=None) -> dict:
    """Aggregate HBM usage over the local devices.

    Uses `Device.memory_stats()` (populated on real TPU/GPU backends;
    None on CPU and on tunneled devices) — absent stats yield zeros so
    callers can log unconditionally."""
    import jax

    devices = devices if devices is not None else jax.local_devices()
    in_use = limit = peak = 0
    n_reporting = 0
    for d in devices:
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:
            pass
        if not stats:
            continue
        n_reporting += 1
        in_use += int(stats.get("bytes_in_use", 0))
        limit += int(stats.get("bytes_limit", 0) or stats.get("bytes_reservable_limit", 0))
        peak += int(stats.get("peak_bytes_in_use", 0))
    frac = (in_use / limit) if limit else 0.0
    return {
        "mem_bytes_in_use": float(in_use),
        "mem_bytes_limit": float(limit),
        "mem_peak_bytes_in_use": float(peak),
        "mem_frac_in_use": float(frac),
        "mem_devices_reporting": float(n_reporting),
    }


def check_memory_kill_threshold(stats: Optional[dict] = None, devices=None):
    """Raise DeviceOOMGuardError when usage exceeds the env threshold.

    No-op when the env var is unset or the backend reports no stats."""
    from areal_tpu.base import env_registry

    threshold = env_registry.get_float(MEMORY_KILL_THRESHOLD_ENV)
    if threshold is None:
        return
    stats = stats if stats is not None else device_memory_stats(devices)
    if stats["mem_bytes_limit"] and stats["mem_frac_in_use"] > threshold:
        raise DeviceOOMGuardError(
            f"device memory {stats['mem_frac_in_use']:.3f} of HBM exceeds "
            f"kill threshold {threshold} "
            f"({stats['mem_bytes_in_use']:.0f}/{stats['mem_bytes_limit']:.0f} "
            f"bytes); terminating for relaunch-recovery"
        )

"""Rollout worker: prompts -> agent episodes -> trajectory push.

Counterpart of the reference's RolloutWorker
(realhf/system/rollout_worker.py:43-372): an async loop that loads the
next prompt, asks the gserver manager for quota (/allocate_rollout —
capacity + staleness gated), runs the agent's episode coroutine with the
PartialRolloutManager servicing its obs queue, reports /finish_rollout,
and pushes accepted trajectories to the trainer over the ZMQ push
stream as JSON.
"""

from __future__ import annotations

import asyncio
import itertools
import time
from typing import Dict, List, Optional

import aiohttp

from areal_tpu.api import data_api
from areal_tpu.api.agent_api import make_agent
from areal_tpu.api.env_api import make_env
from areal_tpu.api.system_api import RolloutWorkerConfig
from areal_tpu.base import constants, env_registry, logging, name_resolve, names, rpc, seeding, tracing
from areal_tpu.base.fault_injection import faults
from areal_tpu.system import eval_scores
from areal_tpu.system.partial_rollout import PartialRolloutManager
from areal_tpu.system.push_pull_stream import NameResolvingZmqPusher
from areal_tpu.system.worker_base import AsyncWorker, PollResult

logger = logging.getLogger("rollout_worker")


class _TracedEnv:
    """Wraps an EnvironmentService so every step (= reward/functioncall
    verification for the single-step envs) records a `reward.verify`
    span under the episode's trace — without touching each agent."""

    def __init__(self, env):
        self._env = env

    def __getattr__(self, name):
        return getattr(self._env, name)

    async def step(self, action):
        with tracing.span("reward.verify"):
            return await self._env.step(action)


class RolloutWorker(AsyncWorker):
    # Class-level defaults so harness-built partial workers (tests
    # construct via __new__) get the manager-blip discipline without
    # running _configure.
    _mgr_fails = 0
    _mgr_policy: Optional[rpc.RetryPolicy] = None
    # Ack/seq mode stays off for partial workers too: their hand-built
    # pushers have no ack socket to drain.
    _wal_acks = False

    @property
    def pending_scores(self) -> Dict[str, float]:
        """Per-episode success rates accumulated locally, merged into the
        shared score file at epoch boundaries (one locked write per epoch,
        not per episode). Lazy so harness-built partial workers work."""
        if not hasattr(self, "_pending_scores"):
            self._pending_scores: Dict[str, float] = {}
        return self._pending_scores

    def _configure(self, config: RolloutWorkerConfig):
        self.cfg = config
        constants.set_experiment_trial_names(
            config.experiment_name, config.trial_name
        )
        seeding.set_random_seed(config.seed, config.worker_name)
        import areal_tpu.agents  # noqa: F401  (registers agents/envs)
        import areal_tpu.datasets  # noqa: F401

        tokenizer = (
            data_api.load_hf_tokenizer(config.tokenizer_path)
            if config.tokenizer_path
            else None
        )
        self.tokenizer = tokenizer
        util = data_api.DatasetUtility(
            seed=config.seed,
            dp_rank=config.worker_index,
            world_size=config.n_rollout_workers,
            tokenizer=tokenizer,
        )
        if len(config.datasets) != 1:
            raise NotImplementedError(
                f"rollout worker supports exactly one dataset, got "
                f"{len(config.datasets)}"
            )
        self.dataset = data_api.make_dataset(config.datasets[0], util)
        # Recovery: resume the curriculum where the previous incarnation
        # left it (reference rollout_worker.py:122-134).
        eval_scores.restore_indices(
            self.dataset,
            config.experiment_name,
            config.trial_name,
            tag=f"rollout{config.worker_index}",
        )
        self.dataloader = data_api.PackedDataLoader(
            self.dataset, batch_size=1, shuffle=True, seed=config.seed
        )
        agent_kwargs = {"tokenizer": tokenizer}
        if "gconfig" not in (config.agent.args or {}):
            import dataclasses as _dc

            agent_kwargs["gconfig"] = _dc.asdict(config.gconfig)
        self.agent = make_agent(config.agent, **agent_kwargs)
        self.env = _TracedEnv(make_env(config.env))
        # Pooled reward-executor discovery: install the process-wide
        # client (functioncall/remote.py). ToolEnv's python tool and
        # math_grader's sympy path route through it whenever an executor
        # fleet is live; with no fleet registered available() is False
        # and everything degrades to the local fork-per-call sandboxes.
        from areal_tpu.functioncall import remote as fc_remote

        fc_remote.register_executor_pool(
            fc_remote.ExecutorPoolClient(
                config.experiment_name, config.trial_name
            )
        )

        self.manager_addr = name_resolve.wait(
            names.gen_server_manager(config.experiment_name, config.trial_name),
            timeout=300,
        )
        # Trainer-via-gateway (system/gateway.py): scheduling hops ride
        # the gateway's /schedule_request trainer proxy, which tags
        # metas as the reserved never-shed ``trainer`` tenant before
        # forwarding to the manager — internal traffic shows up in the
        # usage ledger but can never be queued or rate-limited behind
        # external tenants. allocate/finish stay on the manager either
        # way (they are quota bookkeeping, not serving traffic).
        self._prm_via_gateway = env_registry.get_bool(
            "AREAL_GW_TRAINER_VIA_GATEWAY"
        )
        prm_headers = None
        prm_headers_resolver = None
        if self._prm_via_gateway:
            # The gateway's trainer proxy is internal-token gated (an
            # unauthenticated proxy would let anyone ride the
            # never-shed trainer lane); discovery returns the URL and
            # the token as one consistent per-instance pair, and the
            # resolvers re-read BOTH across a gateway restart.
            from areal_tpu.system.gateway import (
                INTERNAL_TOKEN_HEADER,
                discover_gateway,
                resolve_gateway_once,
            )

            prm_addr, gw_token = discover_gateway(
                config.experiment_name, config.trial_name, timeout=300
            )
            prm_headers = {INTERNAL_TOKEN_HEADER: gw_token}

            def prm_resolver():
                got = resolve_gateway_once(
                    config.experiment_name, config.trial_name
                )
                return got[0] if got else None

            def prm_headers_resolver():
                got = resolve_gateway_once(
                    config.experiment_name, config.trial_name
                )
                return {INTERNAL_TOKEN_HEADER: got[1]} if got else None

        else:
            prm_addr = self.manager_addr
            prm_resolver = lambda: name_resolve.get(  # noqa: E731
                names.gen_server_manager(
                    config.experiment_name, config.trial_name
                )
            )
        self.prm = PartialRolloutManager(
            prm_addr,
            new_tokens_per_chunk=config.new_tokens_per_chunk,
            request_timeout=config.rollout_request_timeout,
            max_retries=config.rollout_max_retries,
            addr_resolver=prm_resolver,
            schedule_headers=prm_headers,
            headers_resolver=prm_headers_resolver,
        )
        # Ack mode rides the WAL switch: with the durable plane armed,
        # every trajectory carries a minted sequence id and stays in the
        # pusher's unacked window until the trainer journals it.
        self._wal_acks = env_registry.get_bool("AREAL_WAL")
        self.pusher = NameResolvingZmqPusher(
            config.experiment_name,
            config.trial_name,
            pusher_index=config.worker_index,
            n_pushers=config.n_rollout_workers,
            n_pullers=config.n_pullers,
            ack=self._wal_acks,
        )
        self._session: Optional[aiohttp.ClientSession] = None
        self._tasks: Dict[str, asyncio.Task] = {}
        self._push_count = 0
        self._episode_counter = itertools.count()
        self._seq_counter = itertools.count()
        self._last_redeliver = 0.0
        self._mgr_policy = rpc.rediscovery_policy()
        self._mgr_fails = 0
        logger.info(
            f"{config.worker_name} configured; manager at {self.manager_addr}"
        )

    def _rediscover_manager(self):
        try:
            addr = name_resolve.get(
                names.gen_server_manager(
                    self.cfg.experiment_name, self.cfg.trial_name
                )
            )
        except name_resolve.NameEntryNotFoundError:
            return
        if addr != self.manager_addr:
            logger.warning(
                f"gserver manager moved {self.manager_addr} -> {addr}"
            )
            self.manager_addr = addr
            # In gateway mode the PRM follows the GATEWAY record via
            # its own addr_resolver, not the manager's.
            if not self._prm_via_gateway:
                self.prm.manager_addr = addr

    async def _http(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=60)
            )
        return self._session

    async def _allocate(self) -> bool:
        sess = await self._http()
        async with sess.post(
            f"{self.manager_addr}/allocate_rollout",
            # Slot ownership: the manager reclaims this worker's
            # outstanding slots if its heartbeat dies.
            json=tracing.inject_into({"worker": self.cfg.worker_name}),
        ) as r:
            d = await r.json()
        return bool(d.get("success"))

    async def _finish(self, accepted: bool):
        sess = await self._http()
        async with sess.post(
            f"{self.manager_addr}/finish_rollout",
            json={"accepted": accepted, "worker": self.cfg.worker_name},
        ) as r:
            await r.json()

    async def _release_quota(self, accepted: bool):
        """Release this episode's quota slot, retrying through transient
        manager failures under the declared RPC policy — a leaked slot
        would permanently shrink the rollout quota (and enough of them
        starve it entirely)."""

        async def attempt(_timeout: float):
            await self._finish(accepted)

        try:
            await rpc.retry_async(
                attempt, policy=rpc.default_policy(attempts=3),
                retryable=(Exception,), what="finish_rollout",
            )
        except rpc.RpcError:
            logger.warning(
                "finish_rollout failed; quota slot leaks until "
                "the manager resyncs", exc_info=True,
            )

    async def rollout_task(self, prompt, trace_parent=None):
        """One episode: agent coroutine + generation servicing
        (reference rollout_task:330)."""
        obs_queue: asyncio.Queue = asyncio.Queue()
        act_queue: asyncio.Queue = asyncio.Queue()
        t_start = time.monotonic()
        # Episode span: the rollout's trace root for everything from the
        # first generation chunk to the trainer's buffer.wait (the
        # allocate span that admitted it is its parent). ep is None when
        # tracing is off.
        ep = tracing.start_span(
            "rollout.episode",
            ctx=trace_parent,
            qid=str(prompt.ids[0]) if prompt.ids else "",
            # getattr: harness-built partial workers (agent tests) have
            # no cfg, and span kwargs evaluate even when tracing is off.
            worker=getattr(getattr(self, "cfg", None), "worker_name", ""),
        )
        ep_gen = {"reprefill_tokens": 0, "interruptions": 0}
        # Task-local: this coroutine runs in its own asyncio Task, so the
        # context needs no reset; tasks created below (generation
        # servicing, the agent) inherit it at create_task time.
        if ep is not None:
            tracing.set_current(ep.ctx)

        seen_qids: set = set()

        async def service_gen():
            # Serve generation requests until the agent finishes — an
            # agent may issue any number of them (multi-turn agents issue
            # one per turn; reference rollout_worker.py:330 loops the
            # same way). The task is cancelled once the agent returns.
            # A repeated qid within the episode is a SESSION CONTINUATION
            # (the agent's next turn on the same conversation): it rides
            # the priority-0 affinity path and re-prefills only the turn
            # delta instead of the whole transcript.
            while True:
                qid, prompt_ids, gconfig = await obs_queue.get()
                continuation = str(qid) in seen_qids
                seen_qids.add(str(qid))
                bundle = await self.prm.generate_group(
                    str(qid), prompt_ids, gconfig,
                    continuation=continuation,
                )
                ep_gen["reprefill_tokens"] += sum(bundle.reprefill_tokens)
                ep_gen["interruptions"] += sum(bundle.n_interruptions)
                await act_queue.put(bundle)

        accepted = False
        gen_task = None
        try:
            faults.maybe_fail("rollout.episode")
            gen_task = asyncio.create_task(service_gen())
            agent_task = asyncio.create_task(
                self.agent.collect_trajectory(
                    prompt, self.env, obs_queue, act_queue
                )
            )
            # service_gen never completes normally; if it finishes first
            # it raised, and the agent would block on act_queue forever —
            # watch both and cancel the agent on gen failure.
            done, _ = await asyncio.wait(
                {gen_task, agent_task}, return_when=asyncio.FIRST_COMPLETED
            )
            if gen_task in done:
                agent_task.cancel()
                try:
                    await agent_task
                except (asyncio.CancelledError, Exception):
                    pass
                raise gen_task.exception() or RuntimeError(
                    "generation servicing exited unexpectedly"
                )
            trajs = await agent_task
            e2e_s = time.monotonic() - t_start
            # Per-row share across ALL of the episode's trajectories
            # (multi-turn agents return several): the consumer sums over
            # batch rows, so the shares must add back to the episode
            # total exactly once.
            ep_rows = sum(t.bs for t in trajs) or 1
            for t in trajs:
                # Group success rates feed the curriculum filter
                # (degenerate groups the agent drops are never scored —
                # the reference's async path behaves the same way).
                for sid, sc in zip(t.ids, t.metadata.get("scores") or []):
                    self.pending_scores[str(sid)] = float(sc)
                # Episode telemetry rides the trajectory metadata to the
                # trainer: e2e latency + interruption re-prefill cost
                # feed the master's perf scalars, the trace context
                # parents the buffer-residency spans. Lists align with
                # ids (SequenceSample contract).
                t.metadata["rollout_e2e_s"] = [e2e_s] * t.bs
                t.metadata["reprefill_tokens"] = (
                    [ep_gen["reprefill_tokens"] / ep_rows] * t.bs
                )
                if ep is not None:
                    t.metadata["trace_ctx"] = [ep.ctx.to_dict()] * t.bs
                seq = (
                    f"{self.cfg.worker_index}/{next(self._seq_counter)}"
                    if self._wal_acks else None
                )
                self.pusher.push(data_api.sample_to_json(t), seq=seq)
                self._push_count += 1
            accepted = bool(trajs)
        except Exception:
            logger.exception("rollout episode failed")
        finally:
            if ep is not None:
                ep.end(
                    accepted=accepted,
                    reprefill_tokens=ep_gen["reprefill_tokens"],
                    interruptions=ep_gen["interruptions"],
                )
            # The quota slot is released on EVERY exit path — normal,
            # crashing agent, or cancellation — so a dying episode can't
            # starve the rollout quota. Shielded so cancellation of this
            # task doesn't also cancel the release mid-flight.
            if gen_task is not None and not gen_task.done():
                gen_task.cancel()
            try:
                await asyncio.shield(self._release_quota(accepted))
            except asyncio.CancelledError:
                pass

    async def _poll_async(self) -> Optional[PollResult]:
        # Experiment status gate (reference rollout_worker.py:216-228).
        # Regression note (areal-lint blocking-async): the name_resolve
        # read is file I/O (NFS-backed in production) and this poll runs
        # on the SAME event loop as every live episode's generate/reward
        # round-trips — an inline read stalled all of them for the
        # duration of one slow stat. Executor keeps the loop serving.
        loop = asyncio.get_running_loop()
        try:
            status = await loop.run_in_executor(
                None,
                lambda: name_resolve.get(
                    names.experiment_status(
                        self.cfg.experiment_name, self.cfg.trial_name
                    )
                ),
            )
            if status in ("COMPLETE", "ABORT"):
                for t in self._tasks.values():
                    t.cancel()
                return None
        except name_resolve.NameEntryNotFoundError:
            pass

        # Reap finished episode tasks, retrieving their exceptions so a
        # crashed episode can't emit "Task exception was never retrieved"
        # at GC time (rollout_task handles its own errors; anything that
        # still escapes is a harness bug worth logging, not crashing on).
        live = {}
        for k, t in self._tasks.items():
            if not t.done():
                live[k] = t
            elif not t.cancelled() and t.exception() is not None:
                logger.error(f"episode task {k} died", exc_info=t.exception())
        self._tasks = live

        if self._wal_acks:
            self.pusher.drain_acks()
            if self.pusher.unacked():
                # Samples past the ack timeout mean the trainer died (or
                # is wedged) before journaling them. A restarted puller
                # re-registers under the same stream name on a NEW port,
                # so re-resolve (file I/O — executor, same rule as the
                # status gate above) and re-target before re-sending.
                # Sockets stay loop-thread-only: reconnect/redeliver run
                # inline here, never on the executor.
                now = time.monotonic()
                if now - self._last_redeliver >= 1.0:
                    self._last_redeliver = now
                    try:
                        addr = await loop.run_in_executor(
                            None,
                            lambda: name_resolve.get(self.pusher.stream_key),
                        )
                    except name_resolve.NameEntryNotFoundError:
                        addr = None
                    if addr:
                        host, port = addr.rsplit(":", 1)
                        self.pusher.reconnect(host, int(port))
                        n = self.pusher.redeliver()
                        if n:
                            logger.warning(
                                "redelivered %d unacked trajectory(ies)", n
                            )

        if len(self._tasks) >= self.cfg.max_concurrent_rollouts:
            await asyncio.sleep(0.02)
            return PollResult(batch_count=0)

        # The allocate span roots the episode's trace: the admission
        # request (and the manager's child span) is the first thing that
        # happens to a rollout, so queue-wait shows up on its timeline.
        alloc_ctx = None
        try:
            with tracing.span(
                "rollout.allocate", worker=self.cfg.worker_name
            ) as alloc_ctx:
                ok = await self._allocate()
        except Exception:
            logger.warning("allocate_rollout failed; retrying", exc_info=True)
            # A restarted gserver manager re-registers at a NEW address;
            # re-resolve so this worker follows it instead of hammering
            # the dead endpoint forever. Off-loop: the lookup is file
            # I/O (areal-lint blocking-async, see poll-gate note above).
            # Backoff comes from the SAME declared rediscovery policy
            # partial_rollout uses (base/rpc.py), so a manager blip has
            # one fleet-wide budget, not two private ones.
            await loop.run_in_executor(None, self._rediscover_manager)
            if self._mgr_policy is None:
                self._mgr_policy = rpc.rediscovery_policy()
            self._mgr_fails += 1
            await asyncio.sleep(self._mgr_policy.backoff(self._mgr_fails))
            return PollResult(batch_count=0)
        self._mgr_fails = 0
        if not ok:
            await asyncio.sleep(0.1)
            return PollResult(batch_count=0)

        try:
            batch, epoch_last = self.dataloader.next_batch()
            if epoch_last:
                # Epoch boundary: publish this worker's scores and run the
                # curriculum filter over the merged file (reference
                # rollout_worker.py:147-176). In-flight episodes from the old
                # epoch still complete; their scores publish next epoch.
                eval_scores.merge_scores(
                    self.cfg.experiment_name,
                    self.cfg.trial_name,
                    self.pending_scores,
                )
                self._pending_scores = {}
                eval_scores.apply_filter(
                    self.dataset,
                    self.cfg.experiment_name,
                    self.cfg.trial_name,
                    tag=f"rollout{self.cfg.worker_index}",
                    min_size=1,
                )
            eid = next(self._episode_counter)
            self._tasks[f"ep{eid}"] = asyncio.create_task(
                self.rollout_task(batch, trace_parent=alloc_ctx)
            )
        except Exception:
            # The slot was allocated but no episode task owns it yet: a
            # failure in this window (dataloader, curriculum filter, task
            # spawn) must give the slot back or the quota leaks.
            await self._release_quota(False)
            raise
        return PollResult(sample_count=1, batch_count=1)

    def _exit_hook(self):
        try:
            # Scores gathered since the last epoch boundary must survive a
            # shutdown/restart — they inform the post-recovery filter.
            eval_scores.merge_scores(
                self.cfg.experiment_name,
                self.cfg.trial_name,
                self.pending_scores,
            )
            self._pending_scores = {}
        except Exception:
            pass
        try:
            self.pusher.close()
        except Exception:
            pass

"""Asyncio sequence buffer: MFC ordering falls out of key readiness.

Counterpart of the reference's buffer (realhf/system/buffer.py:34-408).
The master stores metadata-only `SequenceSample`s here; each MFC's
coroutine awaits a batch whose input keys are all ready and that the MFC
has not consumed yet. Oldest-first selection, per-sample reuse counting
(a sample is garbage-collected once every MFC consumed it).
"""

from __future__ import annotations

import asyncio
import dataclasses
import itertools
import time
from typing import Dict, List, Optional, Set, Tuple

from areal_tpu.api.data_api import SequenceSample
from areal_tpu.api.dfg import MFCDef
from areal_tpu.base import env_registry, logging, tracing
from areal_tpu.base.fault_injection import faults
from areal_tpu.system.wal import SeqLedger

logger = logging.getLogger("buffer")


def parse_task_windows(spec: Optional[str]) -> Dict[str, int]:
    """Parse AREAL_TASK_STALENESS_WINDOWS ('math:2,agentic:8') into
    task tag -> max admitted version lag. Malformed entries are skipped
    loudly — a typo'd window must not silently drop a task's samples."""
    out: Dict[str, int] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        task, sep, win = part.partition(":")
        try:
            if not sep:
                raise ValueError("missing ':'")
            out[task.strip()] = int(win)
        except ValueError:
            logger.warning(
                "ignoring malformed task-staleness entry %r", part
            )
    return out


@dataclasses.dataclass
class _Slot:
    idx: int
    sample: SequenceSample  # metadata-only on the master
    ready_keys: Set[str]
    consumed_by: Set[str]
    birth: float
    sample_id: str
    birth_ns: int = 0  # monotonic-ns enqueue time for residency tracing


class AsyncIOSequenceBuffer:
    """Key-availability-tracking buffer shared by all MFC coroutines.

    put_batch: insert fresh samples (dataset keys ready).
    amend_batch: merge MFC outputs into stored samples, marking new keys.
    get_batch_for_rpc: await `rpc.n_seqs` samples with rpc.input_keys
    ready and rpc not in consumed_by; marks consumption; GCs exhausted
    slots. Mirrors reference buffer.py:247,308,348.
    """

    def __init__(self, rpcs: List[MFCDef], max_size: int = 16384):
        self._rpcs = {r.name: r for r in rpcs}
        self._n_rpcs = len(rpcs)
        self._max_size = max_size
        self._slots: Dict[str, _Slot] = {}  # sample_id -> slot
        self._counter = itertools.count()
        self._cond = asyncio.Condition()
        # Dedup is against RESIDENT ids only: multi-epoch training re-puts
        # the same dataset row ids each epoch, which is legal. Exactly-once
        # across a crash is handled by `ignore_ids` (seeded from recover
        # info): each listed id is skipped once — its pre-crash consumption
        # — then becomes valid again for later epochs.
        self.ignore_ids: Set[str] = set()
        # ids fully consumed since the last epoch boundary (recover dump).
        self.consumed_this_epoch: Set[str] = set()
        # resident duplicates skipped on put (epoch carryover); surfaced
        # in logs so silent data-accounting drift stays visible.
        self.n_dropped_duplicates = 0
        # Exactly-once over rollout sequence ids (wal_seq metadata from
        # the stream dataset): seqs are globally unique, so unlike
        # ignore_ids membership is PERMANENT. Seeded from RecoverInfo at
        # recovery; persisted back at every checkpoint barrier.
        self.seq_ledger = SeqLedger()
        # seq -> resident sample ids not yet fully consumed; a seq is
        # marked in the ledger only once its last id is GC'd.
        self._seq_pending: Dict[str, Set[str]] = {}
        self._id_seq: Dict[str, str] = {}
        # Replayed/redelivered samples dropped at admission because
        # their seq is ledgered or already resident under another id
        # set (prevented duplicates, expected nonzero after recovery).
        self.n_ledger_filtered = 0
        # The invariant DETECTOR, not a dedup count: a sample whose seq
        # was already ledger-marked reaching full consumption again.
        # Expected 0 — the kill-anywhere e2e asserts exactly that.
        # train_stale_dropped counts per-task staleness-window drops at
        # admission (below).
        self.counters = {
            "areal:train_samples_duplicated_total": 0,
            "areal:train_stale_dropped_total": 0,
        }
        # Per-task attribution of the staleness drops above: task tag ->
        # count. Mixed-stream runs (math + agentic through ONE buffer)
        # assert each task's window admits/drops independently; the
        # trainer folds these into perf/task_stale_dropped_<task>.
        self.stale_dropped_by_task: Dict[str, int] = {}
        # Per-task admission windows on top of the gserver manager's
        # GLOBAL allocation gate: a task tag listed here is dropped at
        # put_batch once current_train_step - version_end exceeds its
        # window (math wants tight on-policyness; slow agentic episodes
        # tolerate a loose one). Untagged/unlisted samples keep the
        # global gate only.
        self.task_windows = parse_task_windows(
            env_registry.get_str("AREAL_TASK_STALENESS_WINDOWS")
        )
        # Advanced by the master each step; stamped on buffer.wait spans
        # so the trace report can derive staleness (train step minus the
        # policy version that STARTED the sample's generation).
        self.current_train_step = 0

    def __len__(self):
        return len(self._slots)

    @property
    def size(self) -> int:
        return len(self._slots)

    def resident_ids(self, ids) -> Set[str]:
        """Subset of `ids` currently holding a live slot. Used by the
        step-end cache clear to spare epoch-carryover copies: a consumed
        id that was re-admitted mid-step still needs its tracker entry
        and worker-side data next step."""
        return {i for i in ids if i in self._slots}

    async def put_batch(self, samples: List[SequenceSample]) -> int:
        """Insert samples whose dataset keys are ready. Returns #inserted."""
        async with self._cond:
            # Validate up front so any raise happens before insertion (a
            # mid-loop raise would strand inserted samples without waking
            # consumers). A duplicate id WITHIN one call is always a
            # producer bug and raises; a duplicate of a RESIDENT id is
            # skipped with a warning — multi-epoch training legitimately
            # re-puts row ids whose previous-epoch copy may still await
            # consumption (class contract above), but the skip is counted
            # (`n_dropped_duplicates`) so accounting bugs stay visible.
            new_ids = set()
            resident_dups = set()
            ignored_seen = set()
            ledgered = set()
            stale = set()
            stale_tasks: Dict[str, int] = {}
            for s in samples:
                seqs = s.metadata.get("wal_seq")
                tasks = s.metadata.get("task")
                v_ends = s.metadata.get("version_end")
                for i in range(s.bs):
                    sample_id = s.ids[i]
                    seq = seqs[i] if seqs else None
                    task = tasks[i] if tasks else None
                    win = self.task_windows.get(task) if task else None
                    if win is not None and v_ends:
                        lag = self.current_train_step - int(v_ends[i])
                        if lag > win:
                            stale.add(sample_id)
                            stale_tasks[task] = stale_tasks.get(task, 0) + 1
                            continue
                    if seq is not None and (
                        seq in self.seq_ledger
                        or (seq in self._seq_pending
                            and sample_id not in self._seq_pending[seq])
                    ):
                        # WAL replay / pusher redelivery of a sequence
                        # already consumed (ledgered) or resident: drop
                        # at admission — this is exactly-once working,
                        # counted so recovery accounting stays visible.
                        ledgered.add(sample_id)
                        continue
                    if (
                        sample_id in self.ignore_ids
                        and sample_id not in ignored_seen
                    ):
                        # first occurrence consumes the ignore entry
                        ignored_seen.add(sample_id)
                        continue
                    if sample_id in self._slots:
                        resident_dups.add(sample_id)
                        continue
                    if sample_id in new_ids:
                        raise ValueError(
                            f"duplicate sample id {sample_id!r} within one "
                            f"put_batch call"
                        )
                    new_ids.add(sample_id)
            if ledgered:
                self.n_ledger_filtered += len(ledgered)
                logger.info(
                    "seq ledger filtered %d already-delivered sample(s) at "
                    "admission (total %d)",
                    len(ledgered), self.n_ledger_filtered,
                )
            if stale:
                self.counters["areal:train_stale_dropped_total"] += len(stale)
                for t, n in stale_tasks.items():
                    self.stale_dropped_by_task[t] = (
                        self.stale_dropped_by_task.get(t, 0) + n
                    )
                logger.info(
                    "per-task staleness window dropped %d sample(s) at "
                    "admission (total %d; by task %r)",
                    len(stale),
                    self.counters["areal:train_stale_dropped_total"],
                    dict(self.stale_dropped_by_task),
                )
            if resident_dups:
                self.n_dropped_duplicates += len(resident_dups)
                logger.warning(
                    "skipping %d resident duplicate id(s) (epoch carryover), "
                    "e.g. %r; total skipped: %d",
                    len(resident_dups), next(iter(resident_dups)),
                    self.n_dropped_duplicates,
                )
            if len(self._slots) + len(new_ids) > self._max_size:
                raise RuntimeError(
                    f"buffer overflow: {len(self._slots)} + {len(new_ids)} > "
                    f"max_size={self._max_size}"
                )
            n = 0
            for s in samples:
                seqs = s.metadata.get("wal_seq")
                for sid in range(s.bs):
                    sub = s._select_indices([sid]) if s.bs > 1 else s
                    sample_id = sub.ids[0]
                    if sample_id in ledgered or sample_id in stale:
                        continue
                    if sample_id in self.ignore_ids:
                        # consumed before a crash; skip exactly once
                        self.ignore_ids.discard(sample_id)
                        continue
                    if sample_id in resident_dups:
                        continue
                    seq = seqs[sid] if seqs else None
                    if seq is not None:
                        self._seq_pending.setdefault(seq, set()).add(sample_id)
                        self._id_seq[sample_id] = seq
                    self._slots[sample_id] = _Slot(
                        idx=next(self._counter),
                        sample=sub,
                        ready_keys=set(sub.keys),
                        consumed_by=set(),
                        birth=time.monotonic(),
                        sample_id=sample_id,
                        birth_ns=(
                            tracing.now_ns() if tracing.enabled() else 0
                        ),
                    )
                    n += 1
            if n:
                self._cond.notify_all()
            return n

    async def amend_batch(self, sample: SequenceSample):
        """Merge MFC output keys into the stored samples."""
        async with self._cond:
            for sub in sample.unpack():
                slot = self._slots.get(sub.ids[0])
                if slot is None:
                    logger.warning("amend for unknown sample %s", sub.ids[0])
                    continue
                slot.sample.update_(sub)
                slot.ready_keys |= set(sub.keys)
            self._cond.notify_all()

    def _candidates(self, rpc: MFCDef) -> List[_Slot]:
        need = set(rpc.input_keys)
        return sorted(
            (
                s
                for s in self._slots.values()
                if rpc.name not in s.consumed_by and need <= s.ready_keys
            ),
            key=lambda s: s.idx,
        )

    async def get_batch_for_rpc(
        self, rpc: MFCDef
    ) -> Tuple[List[str], SequenceSample]:
        """Await and consume a batch of rpc.n_seqs samples (oldest first)."""
        # The kill window the ledger exists for: batch handed to
        # training, consumed-seq watermark not yet durable.
        faults.maybe_fail("buffer.consume")
        async with self._cond:
            while True:
                cand = self._candidates(rpc)
                if len(cand) >= rpc.n_seqs:
                    chosen = cand[: rpc.n_seqs]
                    for slot in chosen:
                        slot.consumed_by.add(rpc.name)
                        if tracing.enabled() and slot.birth_ns:
                            # Residency span: enqueue -> this consumption,
                            # parented under the rollout's episode span
                            # with the staleness facts as attributes.
                            # Best-effort: malformed metadata must never
                            # take down batch assembly.
                            try:
                                md = slot.sample.metadata
                                ctx = (md.get("trace_ctx") or [None])[0]
                                v0 = (md.get("version_start") or [-1])[0]
                                v1 = (md.get("version_end") or [-1])[0]
                                tracing.record_span(
                                    "buffer.wait", slot.birth_ns,
                                    ctx=tracing.extract(ctx),
                                    rpc=rpc.name,
                                    # One span per CONSUMING MFC (each
                                    # wait is real); sample_id lets the
                                    # staleness report count each sample
                                    # once despite multi-MFC graphs.
                                    sample_id=str(slot.sample_id),
                                    version_start=int(v0 if v0 is not None else -1),
                                    version_end=int(v1 if v1 is not None else -1),
                                    train_step=int(self.current_train_step),
                                )
                            except Exception:
                                logger.debug(
                                    "buffer.wait span failed", exc_info=True
                                )
                    # GC slots every MFC has consumed.
                    for slot in chosen:
                        if len(slot.consumed_by) == self._n_rpcs:
                            del self._slots[slot.sample_id]
                            self.consumed_this_epoch.add(slot.sample_id)
                            self._mark_consumed(slot.sample_id)
                    ids = [s.sample_id for s in chosen]
                    # Restrict to the rpc's input keys: candidates may have
                    # heterogeneous extra keys (amended at different times),
                    # and gather requires a common key set.
                    keys = list(rpc.input_keys) or None
                    batch = SequenceSample.gather(
                        [s.sample.meta() for s in chosen], keys=keys
                    )
                    return ids, batch
                await self._cond.wait()

    def _mark_consumed(self, sample_id: str):
        """A sample left the buffer fully consumed: once the LAST id of
        its sequence goes, the seq is ledger-marked (and from then on
        admission rejects it forever)."""
        seq = self._id_seq.pop(sample_id, None)
        if seq is None:
            return
        if seq in self.seq_ledger:
            # A ledgered seq reached full consumption AGAIN — the
            # exactly-once invariant broke somewhere upstream. Count it
            # loudly; the kill-anywhere e2e asserts this stays 0.
            self.counters["areal:train_samples_duplicated_total"] += 1
            logger.error(
                "sample %s of already-consumed seq %s trained twice",
                sample_id, seq,
            )
        pending = self._seq_pending.get(seq)
        if pending is not None:
            pending.discard(sample_id)
            if not pending:
                del self._seq_pending[seq]
                self.seq_ledger.mark(seq)

    def consumed_seqs(self) -> Dict:
        """Ledger snapshot for the recover record (checkpoint barrier)."""
        return self.seq_ledger.to_dict()

    def seed_consumed_seqs(self, snapshot: Optional[Dict]):
        """Recovery: re-arm the ledger from the last durable snapshot so
        WAL replay and pusher redelivery filter against the same cut the
        engine state was taken at."""
        self.seq_ledger = SeqLedger.from_dict(snapshot)

    async def poll_ready_count(self, rpc: MFCDef) -> int:
        async with self._cond:
            return len(self._candidates(rpc))

    def on_epoch_boundary(self):
        """Epoch rolled over: prior consumptions are no longer 'this epoch'
        for recovery accounting."""
        self.consumed_this_epoch.clear()

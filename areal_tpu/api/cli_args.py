"""User-facing experiment option dataclasses + `key=value` override CLI.

Counterpart of the reference's cli args module (realhf/api/cli_args.py,
1558 LoC of Hydra structured configs). Hydra/OmegaConf are not available
in this environment, so the same pattern is realized with plain
dataclasses plus a dotted-path `key=value` override parser
(`apply_overrides`) — the experiment classes remain *properties over the
dataclass* exactly like the reference.
"""

from __future__ import annotations

import dataclasses
import json
import typing
from typing import Any, Dict, List, Optional, Tuple

from areal_tpu.api.model_api import GenerationHyperparameters
from areal_tpu.api.system_api import ExperimentSaveEvalControl
from areal_tpu.engine.optimizer import OptimizerConfig


@dataclasses.dataclass
class ModelTrainEvalConfig:
    """One model's build + engine options (reference ModelTrainEvalConfig)."""

    path: Optional[str] = None  # HF checkpoint dir; None = random init
    init_from_scratch: bool = False
    config: Optional[Dict[str, Any]] = None  # TransformerConfig kwargs
    is_critic: bool = False
    dtype: str = "bfloat16"
    optimizer: OptimizerConfig = dataclasses.field(default_factory=OptimizerConfig)
    backend: str = "jax_train"  # jax_train | jax_inference | mock_train
    attn_impl: str = dataclasses.field(
        default="auto",
        metadata={
            "help": "attention impl: auto | splash | flash | reference | "
            "ring | ulysses (ring/ulysses = context parallelism over the "
            "seq mesh axis)"
        },
    )
    remat: bool = True
    mesh_spec: Optional[str] = None  # worker-local mesh, e.g. "d1f4t2"
    row_len_multiple: int = 128
    max_row_len: Optional[int] = None
    prefetch_depth: int = dataclasses.field(
        default=2,
        metadata={
            "help": "overlapped input pipeline depth: a background "
            "thread packs + device_puts micro-batch i+1 while step i "
            "runs on device, bounded to this many staged micro-batches; "
            "0 = fully eager (engine/prefetch.py)"
        },
    )
    stats_fetch_interval: int = dataclasses.field(
        default=1,
        metadata={
            "help": "fetch the packed train stats from device every Nth "
            "train_batch only (each fetch is a host round trip, ~75 ms "
            "on tunneled devices); skipped calls return the last values "
            "tagged <loss>/stats_stale=1"
        },
    )
    # MoE overlay knobs: applied on top of config["moe"] by
    # experiments/common.model_abstraction, so sweeps can flip dispatch
    # or capacity without rewriting the whole nested model config.
    moe_dispatch: Optional[str] = dataclasses.field(
        default=None,
        metadata={
            "help": "override config['moe']['dispatch'] for this model: "
            "'capacity' (einsum, drops beyond capacity) or 'dropless' "
            "(ragged grouped matmul; expert-parallel when the fsdp "
            "mesh axis divides num_experts)"
        },
    )
    moe_capacity_factor: Optional[float] = dataclasses.field(
        default=None,
        metadata={
            "help": "override config['moe']['capacity_factor'] "
            "(capacity dispatch only; >= num_experts/top_k guarantees "
            "zero drops)"
        },
    )
    moe_aux_loss_coef: Optional[float] = dataclasses.field(
        default=None,
        metadata={
            "help": "override config['moe']['aux_loss_coef'] (the "
            "Switch load-balance loss weight)"
        },
    )


@dataclasses.dataclass
class MFCConfig:
    """Per-MFC micro-batching override (reference MFCConfig,
    api/cli_args.py: each model function call carries its own
    MicroBatchSpec + allocation). None fields inherit the experiment's
    global `mb_spec_n_mbs` / `mb_spec_max_tokens`."""

    n_mbs: Optional[int] = dataclasses.field(
        default=None,
        metadata={"help": "split this MFC's batch into n micro-batches"},
    )
    max_tokens_per_mb: Optional[int] = dataclasses.field(
        default=None,
        metadata={
            "help": "cap tokens per micro-batch for this MFC "
            "(balanced-packing split)"
        },
    )


@dataclasses.dataclass
class PPOHyperparameters:
    """Mirrors reference PPOHyperparameters (api/cli_args.py)."""

    gconfig: GenerationHyperparameters = dataclasses.field(
        default_factory=lambda: GenerationHyperparameters(
            max_new_tokens=512, top_p=1.0, temperature=1.0
        )
    )
    group_size: int = 1
    # Best-of-k: sample this many responses per prompt, verify, train on
    # the top `group_size` (None disables; reference
    # ppo_interface.py:376-408).
    generation_size: Optional[int] = dataclasses.field(
        default=None,
        metadata={
            "help": "sample-then-select: candidates per prompt before "
            "keeping the best group_size"
        },
    )
    ppo_n_minibatches: int = 4
    eps_clip: float = 0.2
    c_clip: Optional[float] = None
    value_eps_clip: float = 0.2
    disable_value: bool = True  # group-reward baseline by default (GRPO-style)
    reward_output_scaling: float = 1.0
    reward_output_bias: float = 0.0
    max_reward_clip: float = 20.0
    mask_no_eos_with_zero: bool = False
    discount: float = 1.0
    gae_lambda: float = 1.0
    adv_norm: bool = True
    group_adv_norm: bool = False
    kl_ctl: float = 0.1
    use_adaptive_kl_ctl: bool = False
    use_decoupled_loss: bool = False
    behav_imp_weight_cap: Optional[float] = None
    # 'global' | 'dp': gradient token-normalization scope (reference
    # ppo_interface.py:253; see JaxTrainEngine.train_batch).
    token_normalize_scope: str = "global"
    recompute_logprob: bool = True
    fuse_rew_ref: bool = False
    success_rate_lb: float = 0.0
    success_rate_ub: float = 1.0
    # async controls
    max_head_offpolicyness: int = 0
    new_tokens_per_chunk: int = 1 << 30
    max_concurrent_rollouts: int = 32


@dataclasses.dataclass
class DatasetConfig:
    path: Optional[str] = None
    max_length: Optional[int] = 1024
    type_: str = "math_code_prompt"
    args: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class BaseExperimentConfig:
    """Fields shared by every experiment (reference CommonExperimentConfig,
    experiments/common/common.py:72)."""

    experiment_name: str = "exp"
    trial_name: str = "trial"
    seed: int = 1
    total_train_epochs: int = 1
    train_batch_size: int = 8
    tokenizer_path: Optional[str] = None
    dataset: DatasetConfig = dataclasses.field(default_factory=DatasetConfig)
    exp_ctrl: ExperimentSaveEvalControl = dataclasses.field(
        default_factory=ExperimentSaveEvalControl
    )
    # "d2t4" or decoupled "gen.d1t1+d1t1"; data axis -> #model workers for
    # the single-host local launcher.
    allocation_mode: str = "d1"
    n_model_workers: int = 1
    train_n_hosts: int = dataclasses.field(
        default=1,
        metadata={
            "help": "host processes sharing ONE train mesh via "
            "jax.distributed: each model worker becomes one host of the "
            "train partition (coordinator elected through name_resolve, "
            "parallel/distributed.setup_host_group), builds the GLOBAL "
            "allocation_mode train mesh, and iterates the dataset in "
            "lockstep (dp handled inside the mesh, not across workers). "
            "1 = single-host (worker-local meshes, the default)"
        },
    )
    recover_mode: str = "disabled"  # disabled | auto | resume
    recover_retries: int = 1
    # Per-worker fault domain: serving-plane workers (generation server /
    # rollout worker / gserver manager) that die or hang are restarted in
    # place this many times each before the failure escalates to the
    # whole-experiment relaunch above.
    worker_restarts: int = 2
    name_resolve_backend: str = "nfs"
    name_resolve_root: Optional[str] = None
    mb_spec_n_mbs: int = 1
    mb_spec_max_tokens: Optional[int] = None
    # Automatic per-checkpoint offline evaluation (reference
    # scheduler/evaluator.py AutomaticEvaluator, enabled via auto_eval):
    # watches the save dir while training runs and submits one eval job
    # per new checkpoint through the scheduler client.
    auto_eval: bool = False
    auto_eval_data_path: Optional[str] = None  # benchmark jsonl
    auto_eval_task: str = "math"  # math | code
    auto_eval_model_role: str = "default"  # "actor" for PPO experiments
    auto_eval_max_new_tokens: int = 512
    auto_eval_max_concurrent_jobs: int = 1
    # JAX platform for eval jobs: "cpu" (default) keeps them off the
    # accelerator the training workers exclusively hold.
    auto_eval_device: str = "cpu"


@dataclasses.dataclass
class SFTExpConfig(BaseExperimentConfig):
    model: ModelTrainEvalConfig = dataclasses.field(
        default_factory=ModelTrainEvalConfig
    )

    def __post_init__(self):
        if self.dataset.type_ == "math_code_prompt":
            self.dataset.type_ = "prompt_answer"


@dataclasses.dataclass
class PPOMATHExpConfig(BaseExperimentConfig):
    """Sync PPO on math/code prompts (reference PPOMATHConfig)."""

    actor: ModelTrainEvalConfig = dataclasses.field(
        default_factory=ModelTrainEvalConfig
    )
    ref: Optional[ModelTrainEvalConfig] = None  # default: copy of actor path
    critic: Optional[ModelTrainEvalConfig] = None  # None when disable_value
    ppo: PPOHyperparameters = dataclasses.field(default_factory=PPOHyperparameters)
    group_size: int = 1
    # Per-MFC micro-batch overrides (reference PPOMATHConfig exposes one
    # MFCConfig per function call; e.g. `actor_train.n_mbs=8
    # actor_gen.max_tokens_per_mb=65536`).
    actor_gen: MFCConfig = dataclasses.field(default_factory=MFCConfig)
    actor_train: MFCConfig = dataclasses.field(default_factory=MFCConfig)
    rew_inf: MFCConfig = dataclasses.field(default_factory=MFCConfig)
    ref_inf: MFCConfig = dataclasses.field(default_factory=MFCConfig)
    critic_inf: MFCConfig = dataclasses.field(default_factory=MFCConfig)
    critic_train: MFCConfig = dataclasses.field(default_factory=MFCConfig)

    def __post_init__(self):
        if self.group_size > 1:
            self.ppo.group_size = self.group_size


@dataclasses.dataclass
class AsyncPPOMATHExpConfig(PPOMATHExpConfig):
    """Async PPO: decoupled generation + streaming rollouts
    (reference AsyncPPOMATHConfig)."""

    n_rollout_workers: int = 1
    n_generation_servers: int = 1
    gen_max_concurrent_requests: int = 32
    gen_max_seq_len: int = 4096
    gen_decode_block_steps: int = 16
    gen_kv_page_size: int = 128
    # Paged KV pool capacity in tokens (None = B * max_seq_len); sizing it
    # below that serves long contexts in bounded HBM with
    # preempt-and-resubmit under pressure (engine/serving.py).
    gen_kv_pool_tokens: Optional[int] = None
    # Shard each generation server over this many devices (GSPMD TP).
    gen_tensor_parallel: int = 1
    # Prefill shape buckets: prompts are padded up to a multiple of this,
    # bounding the number of compiled prefill programs.
    gen_prompt_bucket: int = 64
    # Max prompts admitted into one batched prefill.
    gen_prefill_max_batch: int = 8
    # Chunked prefill threshold/size for long prompts (None disables).
    gen_prefill_chunk: Optional[int] = dataclasses.field(
        default=None,
        metadata={
            "help": "prompts longer than this prefill in fixed-size "
            "chunks through one compiled program (16-32k contexts)"
        },
    )
    # Cap on chunked/cache-hit prefills admitted per serve-loop lap
    # (they run sequentially and stall decode for running slots).
    gen_chunked_prefill_per_lap: int = dataclasses.field(
        default=2,
        metadata={
            "help": "max one-at-a-time chunked prefills admitted per "
            "serve-loop lap; bounds decode-latency jitter"
        },
    )
    # Prefix KV reuse budget for partial-rollout resubmissions.
    gen_prefix_cache_tokens: Optional[int] = dataclasses.field(
        default=None,
        metadata={
            "help": "token budget for qid-keyed prefix KV reuse; "
            "resubmissions prefill only the delta (None disables)"
        },
    )
    # KV pool precision on the generation servers.
    gen_kv_cache_dtype: Optional[str] = dataclasses.field(
        default=None,
        metadata={
            "help": "KV pool precision: None/'model' stores the compute "
            "dtype; 'int8' stores quantized pages (half the decode HBM "
            "traffic, double the tokens per pool budget)"
        },
    )
    # N-gram (prompt-lookup) speculative decoding on the gen servers.
    gen_speculative_draft_len: int = dataclasses.field(
        default=0,
        metadata={
            "help": "tokens drafted per decode step via n-gram prompt "
            "lookup; verified prefix kept (lossless). 0 disables"
        },
    )
    gen_speculative_ngram: int = dataclasses.field(
        default=2,
        metadata={"help": "n-gram length for the draft lookup match"},
    )
    gen_speculative_window: Optional[int] = dataclasses.field(
        default=None,
        metadata={
            "help": "backward search window (tokens) for the n-gram "
            "draft lookup: only the most recent W candidate positions "
            "are matched, so draft cost stops scaling with max_seq_len "
            "at 16-32k contexts. None = engine default (1024); 0 = "
            "unbounded full-history scan"
        },
    )
    gen_decode_weight_dtype: Optional[str] = dataclasses.field(
        default=None,
        metadata={
            "help": "decode-path weight precision: 'int8' halves the "
            "per-step weight stream (prefill stays bf16); None disables"
        },
    )
    # Streaming weight-distribution plane (system/weight_plane.py).
    gen_weight_plane: bool = dataclasses.field(
        default=False,
        metadata={
            "help": "distribute weight updates over a peer-fanout tree "
            "(origin uploads each byte once; servers serve chunks to "
            "siblings) instead of every generation server re-reading "
            "the checkpoint from NFS; transfer overlaps serving, the "
            "interrupt+swap cutover is measured separately"
        },
    )
    gen_weight_chunk_mb: int = dataclasses.field(
        default=8,
        metadata={
            "help": "weight-plane chunk size (MiB): per-chunk content "
            "hashes + HTTP Range resume, so a torn transfer re-pays at "
            "most one chunk"
        },
    )
    gen_weight_fanout: int = dataclasses.field(
        default=2,
        metadata={
            "help": "children per node in the weight-plane fanout tree; "
            "origin egress is bounded by fanout * payload"
        },
    )
    gen_weight_cutover_budget_s: float = dataclasses.field(
        default=3.0,
        metadata={
            "help": "target bound for the serve-interrupting weight "
            "cutover window (the reference's <3s weight-update bar); "
            "overruns are surfaced in /status + logs, not fatal"
        },
    )
    gen_weight_wire_dtype: Optional[str] = dataclasses.field(
        default=None,
        metadata={
            "help": "'int8' ships weight updates over the plane as "
            "quantized data+scale streams (~half the bytes per "
            "version; servers dequantize at assembly). The trainer "
            "dump publishes the companion bin; None ships raw bytes"
        },
    )
    gen_weight_shards: str = dataclasses.field(
        default="",
        metadata={
            "help": "comma-separated 'rank/degree' weight-shard spec "
            "per generation server index (e.g. '0/2,1/2' for a 2-way "
            "fleet TP group): each server fetches only its slice of "
            "every weight version and same-shard peers fan chunks to "
            "each other. Empty entries = unsharded (full payload)"
        },
    )

    # Disaggregated prefill/decode serving (docs/serving.md).
    gen_server_roles: str = dataclasses.field(
        default="",
        metadata={
            "help": "comma-separated pool role per generation server "
            "index (prefill|decode|unified); empty/short lists pad "
            "with 'unified'. E.g. 'prefill,decode,unified' splits a "
            "3-server fleet with one elastic spare"
        },
    )
    gen_kv_handoff_compress: Optional[str] = dataclasses.field(
        default=None,
        metadata={
            "help": "'int8' quantizes exported KV-handoff blobs "
            "(halves the prefill->decode hop; importer dequantizes). "
            "None ships the pool's own precision"
        },
    )
    # Tiered KV plane (docs/serving.md "KV tiering + global prefix
    # index").
    gen_kv_tier_mb: Optional[int] = dataclasses.field(
        default=None,
        metadata={
            "help": "host-RAM KV tier capacity (MiB) per generation "
            "server: prefix-cache evictions spill there (handoff wire "
            "format) instead of being freed, and returning sessions "
            "restore instead of re-prefilling. None = "
            "AREAL_KV_TIER_BYTES (default off)"
        },
    )
    gen_kv_tier_disk_dir: Optional[str] = dataclasses.field(
        default=None,
        metadata={
            "help": "optional local-disk second KV tier directory "
            "(host-LRU evictions demote there, hash-verified on "
            "read-back). None = AREAL_KV_TIER_DISK_DIR"
        },
    )
    gen_kv_spill_dtype: Optional[str] = dataclasses.field(
        default=None,
        metadata={
            "help": "'int8' quantizes FLOAT KV pools' prefixes on the "
            "spill wire (halves tier bytes; int8 pools always spill "
            "their data+scales form). None = AREAL_KV_SPILL_DTYPE"
        },
    )
    gen_kv_index_size: Optional[int] = dataclasses.field(
        default=None,
        metadata={
            "help": "LRU cap on the manager's global prefix index "
            "(qid -> holder + tier; lets ANY server serve a returning "
            "session by pulling its prefix from the holder). None = "
            "AREAL_KV_INDEX_SIZE; 0 disables index-aware routing"
        },
    )
    gen_elastic_pools: bool = dataclasses.field(
        default=False,
        metadata={
            "help": "let the manager re-role 'unified'-configured "
            "servers between the prefill and decode pools from "
            "queue-depth/free-page watermarks (drain + flip, weights "
            "stay resident)"
        },
    )
    gen_prefill_queue_high_tokens: int = dataclasses.field(
        default=4096,
        metadata={
            "help": "queued-prompt-token watermark over the prefill "
            "pool at which an elastic decode-side server flips to "
            "prefill"
        },
    )
    gen_prefill_queue_low_tokens: int = dataclasses.field(
        default=0,
        metadata={
            "help": "queued-prompt-token floor at or below which a "
            "manager-flipped prefill server returns to its original "
            "pool"
        },
    )
    gen_decode_free_page_min_frac: float = dataclasses.field(
        default=0.1,
        metadata={
            "help": "decode-pool free-KV-page floor (fraction): below "
            "it an elastic prefill-side server flips to decode"
        },
    )
    gen_elastic_fleet: bool = dataclasses.field(
        default=True,
        metadata={
            "help": "elastic fleet control plane: adopt runtime "
            "joiners (peer weight bootstrap before routing), forget "
            "graceful drain departures, persist the manager HA lease "
            "(system/fleet_controller.py). False = fixed fleet"
        },
    )
    gen_autoscale: bool = dataclasses.field(
        default=False,
        metadata={
            "help": "watermark autoscaling of the generation fleet: "
            "scale-out/in from the queued-token / free-page signals "
            "(requires a launcher attached to the manager)"
        },
    )
    gen_scale_out_queued_tokens: int = dataclasses.field(
        default=4096,
        metadata={
            "help": "fleet-average queued prompt tokens per routable "
            "server at/above which the autoscaler launches a server"
        },
    )
    gen_scale_in_queued_tokens: int = dataclasses.field(
        default=64,
        metadata={
            "help": "fleet-average queued prompt tokens at/below "
            "which the autoscaler drains the least-loaded server"
        },
    )
    gen_pool_min_servers: int = dataclasses.field(
        default=1,
        metadata={"help": "autoscaler floor on fleet size"},
    )
    gen_pool_max_servers: int = dataclasses.field(
        default=8,
        metadata={"help": "autoscaler ceiling on fleet size"},
    )
    schedule_policy: str = "round_robin"
    # rollout agent: "math-single-step" | "math-multi-turn" | "tool-use"
    agent_type: str = "math-single-step"
    agent_num_turns: int = 4
    agent_turn_discount: float = 1.0
    # tool-use agent only: deterministic tool turns before the model is
    # trusted to emit its own <tool:...> calls (0 = fully model-driven).
    agent_scripted_tool_turns: int = 0

    def __post_init__(self):
        super().__post_init__()
        # Config-parse-time validation: bad serving precisions and
        # malformed weight-shard specs must fail HERE, not at engine
        # construction deep inside server startup.
        if self.gen_decode_weight_dtype not in (None, "model", "int8"):
            raise ValueError(
                f"gen_decode_weight_dtype="
                f"{self.gen_decode_weight_dtype!r}: expected None, "
                f"'model', or 'int8'"
            )
        if self.gen_weight_wire_dtype not in (None, "int8"):
            raise ValueError(
                f"gen_weight_wire_dtype={self.gen_weight_wire_dtype!r}: "
                f"expected None or 'int8'"
            )
        for i, spec in enumerate(parse_weight_shards(
            self.gen_weight_shards, self.n_generation_servers
        )):
            # The engine can only place a sliced cutover when its mesh
            # tensor extent matches the fleet shard degree — catch the
            # mismatch here, not after a full fleet transfer.
            if spec is not None and spec[1] != self.gen_tensor_parallel:
                raise ValueError(
                    f"gen_weight_shards[{i}] degree {spec[1]} != "
                    f"gen_tensor_parallel {self.gen_tensor_parallel}"
                )


def parse_weight_shards(
    spec: str, n_servers: int
) -> List[Optional[Tuple[int, int]]]:
    """'0/2,1/2' -> [(0, 2), (1, 2), ...] padded with None (unsharded)
    per generation-server index; raises ValueError on malformed or
    out-of-range entries."""
    entries = (spec or "").split(",")
    if spec and len(entries) > n_servers:
        raise ValueError(
            f"gen_weight_shards lists {len(entries)} entries for "
            f"{n_servers} generation server(s)"
        )
    out: List[Optional[Tuple[int, int]]] = []
    for i, ent in enumerate(entries):
        ent = ent.strip()
        if not ent:
            out.append(None)
            continue
        try:
            rank_s, degree_s = ent.split("/")
            rank, degree = int(rank_s), int(degree_s)
        except ValueError:
            raise ValueError(
                f"gen_weight_shards[{i}]={ent!r}: expected 'rank/degree'"
            )
        if degree < 1 or not (0 <= rank < degree):
            raise ValueError(
                f"gen_weight_shards[{i}]={ent!r}: rank out of range"
            )
        out.append((rank, degree))
    out += [None] * (n_servers - len(out))
    return out[:n_servers]


# ---------------------------------------------------------------------------
# Option discovery (`--help-config`)
# ---------------------------------------------------------------------------


def describe_options(cfg: Any, prefix: str = "") -> List[Dict[str, Any]]:
    """Walk a (possibly nested) config dataclass and return one row per
    reachable dotted override path: {path, type, default, help}. This is
    the counterpart of the reference's Hydra `--help` surface — every row
    is directly usable as a `key=value` CLI override."""
    rows: List[Dict[str, Any]] = []
    cls = type(cfg) if not isinstance(cfg, type) else cfg
    obj = cfg if not isinstance(cfg, type) else None
    hints = typing.get_type_hints(cls)
    for f in dataclasses.fields(cls):
        path = f"{prefix}{f.name}"
        val = getattr(obj, f.name) if obj is not None else (
            f.default
            if f.default is not dataclasses.MISSING
            else (
                f.default_factory()
                if f.default_factory is not dataclasses.MISSING
                else None
            )
        )
        typ = hints.get(f.name, f.type)
        nested = val if dataclasses.is_dataclass(val) else None
        if nested is None:
            # Optional[dataclass] fields defaulting to None still expose
            # their subtree (apply_overrides instantiates on demand).
            for cand in typing.get_args(typ) or ():
                if dataclasses.is_dataclass(cand):
                    nested = cand()
                    break
        if nested is not None:
            rows.extend(describe_options(nested, prefix=f"{path}."))
            continue
        rows.append(
            {
                "path": path,
                "type": getattr(typ, "__name__", str(typ)),
                "default": val,
                "help": f.metadata.get("help", ""),
            }
        )
    return rows


def format_options(cfg: Any) -> str:
    rows = describe_options(cfg)
    width = max(len(r["path"]) for r in rows) + 2
    lines = [
        f"{type(cfg).__name__ if not isinstance(cfg, type) else cfg.__name__}"
        f" options (override with dotted key=value):"
    ]
    for r in rows:
        help_txt = f"  # {r['help']}" if r["help"] else ""
        lines.append(
            f"  {r['path']:<{width}}= {r['default']!r}{help_txt}"
        )
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# key=value override parsing
# ---------------------------------------------------------------------------


def _coerce(value: str, typ) -> Any:
    origin = typing.get_origin(typ)
    if origin is typing.Union:
        args = [a for a in typing.get_args(typ) if a is not type(None)]
        if value.lower() in ("none", "null"):
            return None
        return _coerce(value, args[0]) if args else value
    if typ is bool:
        return value.lower() in ("1", "true", "yes", "on")
    if typ is int:
        return int(value)
    if typ is float:
        return float(value)
    if typ in (dict, Dict, Any) or origin in (dict, list) or typ is list:
        return json.loads(value)
    return value


def apply_overrides(cfg: Any, overrides: List[str]) -> Any:
    """Apply `a.b.c=value` overrides in place onto nested dataclasses."""
    hints_cache: Dict[type, Dict[str, Any]] = {}
    for ov in overrides:
        if "=" not in ov:
            raise ValueError(f"override {ov!r} is not key=value")
        path, value = ov.split("=", 1)
        obj = cfg
        parts = path.split(".")
        for p in parts[:-1]:
            if not hasattr(obj, p):
                raise AttributeError(f"no field {p!r} on {type(obj).__name__}")
            nxt = getattr(obj, p)
            if nxt is None and dataclasses.is_dataclass(obj):
                # Instantiate Optional nested dataclasses on demand so
                # e.g. `critic.path=/ckpt` works when critic defaults None.
                cls = type(obj)
                if cls not in hints_cache:
                    hints_cache[cls] = typing.get_type_hints(cls)
                typ = hints_cache[cls].get(p)
                inner = None
                for cand in typing.get_args(typ) or (typ,):
                    if dataclasses.is_dataclass(cand):
                        inner = cand
                        break
                if inner is None:
                    raise AttributeError(
                        f"field {p!r} is None and not a dataclass "
                        f"(declared type: {typ})"
                    )
                nxt = inner()
                setattr(obj, p, nxt)
            obj = nxt
        leaf = parts[-1]
        if dataclasses.is_dataclass(obj):
            cls = type(obj)
            if cls not in hints_cache:
                hints_cache[cls] = typing.get_type_hints(cls)
            if leaf not in hints_cache[cls]:
                raise AttributeError(f"no field {leaf!r} on {cls.__name__}")
            setattr(obj, leaf, _coerce(value, hints_cache[cls][leaf]))
        elif isinstance(obj, dict):
            obj[leaf] = json.loads(value) if value[:1] in "[{" else value
        else:
            raise AttributeError(f"cannot set {leaf!r} on {type(obj)}")
    return cfg

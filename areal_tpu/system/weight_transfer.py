"""Trainer -> generation-server weight transfer with a same-host fast path.

Counterpart of the reference's param-realloc transfer stack
(realhf/system/model_worker.py:1046-1148 — disk-mediated by default, with
NCCL/GDRDMA fast paths keeping it under the <3 s bar of
blog/AReaL_v0_2.md:52-54). The TPU single-host equivalent of the CUDA-IPC
path is raw parameter bytes in tmpfs (/dev/shm) read back with mmap: no
pickle serialize/deserialize copies, no disk IO, and `jax.device_put`
streams straight from the mapped pages. The pickle-on-NFS dump
(engine/checkpoint.py) remains the cross-host fallback.

Format (per dump directory):
- ``params-v{N}.bin``  — every leaf's contiguous bytes, concatenated.
- ``params.json``      — manifest: schema version, dump version N, bin
  filename, and per-leaf (path, dtype, shape, offset). Written via
  tmp+rename AFTER the bin, so a reader that sees a manifest always sees
  its complete bin. Older bins are garbage-collected down to the last 2;
  a reader racing the GC gets FileNotFoundError and falls back.

The tree is assumed to be nested dicts of arrays (what
models/transformer.init_params builds); list/tuple nodes are rejected at
dump time rather than silently mis-rebuilt.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from areal_tpu.base import logging
from areal_tpu.base.chunking import DEFAULT_CHUNK_BYTES, StreamChunker

logger = logging.getLogger("weight_transfer")

_MANIFEST = "params.json"
_SCHEMA = 1


class WeightVersionMismatch(RuntimeError):
    """load_for_serving found weights, but not the requested version.

    Serving them anyway would pin a stale (or unverifiable, version -1
    pickle/HF) dump under the new version label — the exact accounting
    hole the staleness gate can't see. Callers fail the update instead;
    the manager's eviction/readmission path re-syncs the server."""


def shm_transfer_dir(experiment_name: str, trial_name: str, role: str) -> Optional[str]:
    """tmpfs dump directory for the same-host fast path, or None when
    /dev/shm is unavailable (then only the disk path is used)."""
    base = "/dev/shm"
    if not os.path.isdir(base) or not os.access(base, os.W_OK):
        return None
    return os.path.join(base, "areal_tpu", experiment_name, trial_name, role)


def _flatten(params: Any, prefix: Tuple[str, ...] = ()) -> list:
    out = []
    if isinstance(params, dict):
        for k in sorted(params.keys()):
            out.extend(_flatten(params[k], prefix + (str(k),)))
        return out
    if isinstance(params, (list, tuple)):
        raise TypeError(
            f"weight_transfer supports dict-of-array trees only; found "
            f"{type(params).__name__} at {'/'.join(prefix)}"
        )
    return [("/".join(prefix), params)]


def chunk_sidecar_name(bin_name: str) -> str:
    """Chunk-index sidecar for a bin (``params-v{N}.chunks.json``)."""
    return bin_name[: -len(".bin")] + ".chunks.json"


def dump_raw_params(
    params: Any, dump_dir: str, version: int,
    chunk_bytes: int = DEFAULT_CHUNK_BYTES,
) -> float:
    """Write the raw dump; returns seconds spent. Safe against concurrent
    readers (see module docstring); single writer assumed (the dp-rank-0
    dump rule, system/model_worker._param_realloc).

    Also publishes a ``params-v{N}.chunks.json`` sidecar: the content
    hashes of the bin's fixed-size chunks, computed while the bytes
    stream through this loop anyway — the weight-plane origin serves its
    chunk manifest from this instead of re-reading + re-hashing the
    whole bin on every version bump (``chunk_bytes`` should match the
    plane's ``weight_chunk_bytes`` knob; a mismatched sidecar is simply
    ignored by the reader)."""
    t0 = time.monotonic()
    os.makedirs(dump_dir, exist_ok=True)
    leaves = _flatten(params)
    bin_name = f"params-v{version}.bin"
    manifest: Dict[str, Any] = {
        "schema": _SCHEMA, "version": int(version), "bin": bin_name,
        "leaves": [],
    }
    offset = 0
    chunker = StreamChunker(chunk_bytes)
    tmp_bin = os.path.join(dump_dir, bin_name + f".tmp.{os.getpid()}")
    with open(tmp_bin, "wb") as f:
        for path, leaf in leaves:
            arr = np.ascontiguousarray(np.asarray(leaf))
            data = arr.tobytes()
            f.write(data)
            chunker.update(data)
            # dtype.name (not .str): ml_dtypes types like bfloat16 have
            # .str '<V2' which round-trips to a raw void type.
            manifest["leaves"].append(
                {"path": path, "dtype": arr.dtype.name,
                 "shape": list(arr.shape), "offset": offset}
            )
            offset += arr.nbytes
        # fsync BEFORE the rename pair below: rename ordering alone is
        # only crash-safe within one file. Without it a host crash can
        # persist the (later-written) manifest but not the bin's data
        # blocks — a manifest pointing at unsynced bytes that would pass
        # the size check and serve garbage weights.
        f.flush()
        os.fsync(f.fileno())
    manifest["total_bytes"] = offset
    os.replace(tmp_bin, os.path.join(dump_dir, bin_name))
    sidecar = chunk_sidecar_name(bin_name)
    tmp_sc = os.path.join(dump_dir, sidecar + f".tmp.{os.getpid()}")
    with open(tmp_sc, "w") as f:
        json.dump(chunker.finish(), f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp_sc, os.path.join(dump_dir, sidecar))
    tmp_man = os.path.join(dump_dir, _MANIFEST + f".tmp.{os.getpid()}")
    with open(tmp_man, "w") as f:
        json.dump(manifest, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp_man, os.path.join(dump_dir, _MANIFEST))
    # GC old bins + their sidecars (keep the newest 2 so an in-flight
    # reader can finish).
    bins = sorted(
        (b for b in os.listdir(dump_dir)
         if b.startswith("params-v") and b.endswith(".bin")),
        key=lambda b: int(b[len("params-v"):-len(".bin")]),
    )
    for b in bins[:-2]:
        for victim in (b, chunk_sidecar_name(b)):
            try:
                os.unlink(os.path.join(dump_dir, victim))
            except OSError:
                pass
    return time.monotonic() - t0


def unflatten_leaves(leaves: Dict[str, np.ndarray]) -> Any:
    """path->array mapping back into the nested-dict pytree (shared with
    the weight plane's host-buffer assembly, engine/weight_client.py)."""
    root: Dict[str, Any] = {}
    for path, arr in leaves.items():
        node = root
        parts = path.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return root


def _read_manifest(dump_dir: str) -> Optional[Dict[str, Any]]:
    try:
        with open(os.path.join(dump_dir, _MANIFEST)) as f:
            manifest = json.load(f)
    except (OSError, ValueError, json.JSONDecodeError):
        return None
    if manifest.get("schema") != _SCHEMA:
        return None
    return manifest


def load_raw_params(dump_dir: str) -> Optional[Tuple[Any, int]]:
    """mmap the latest raw dump: (params pytree of memory-mapped arrays,
    dump version), or None if absent/torn (caller falls back).

    A reader can race the dump GC: the manifest it read names a bin the
    writer just unlinked (GC keeps only the newest 2). That race means a
    NEWER dump exists — re-read the manifest once and retry against it
    rather than silently falling through to a stale pickle."""
    import ml_dtypes  # noqa: F401  registers bfloat16 et al. by name

    for _attempt in range(2):
        manifest = _read_manifest(dump_dir)
        if manifest is None:
            return None
        try:
            mm = np.memmap(
                os.path.join(dump_dir, manifest["bin"]), mode="r",
                dtype=np.uint8,
            )
        except FileNotFoundError:
            continue  # GC race: refreshed manifest names the new bin
        except (OSError, ValueError, KeyError):
            return None  # malformed manifest: caller falls back
        try:
            if mm.size != manifest["total_bytes"]:
                return None  # torn write
            leaves = {}
            for e in manifest["leaves"]:
                dt = np.dtype(e["dtype"])
                n = int(np.prod(e["shape"])) * dt.itemsize
                leaves[e["path"]] = (
                    mm[e["offset"]: e["offset"] + n].view(dt).reshape(e["shape"])
                )
            return unflatten_leaves(leaves), int(manifest["version"])
        except (ValueError, KeyError):
            return None
    return None


def _load_once(
    model_path: str,
    shm_dir: Optional[str],
    t0: float,
    want_version: Optional[int] = None,
    raw_seen: Optional[Dict[str, int]] = None,
):
    """One pass down the fallback chain. With ``want_version`` pinned, a
    raw dump holding the WRONG version falls through to the next source
    instead of shadowing it — e.g. a tmpfs dump lagging one version
    behind the NFS dump (writer crashed between the two dumps) must not
    hide the matching disk copy. Mismatched raw versions are recorded in
    ``raw_seen`` for the caller's error message."""
    if shm_dir is not None:
        got = load_raw_params(shm_dir)
        if got is not None:
            params, v = got
            if want_version is None or v == want_version:
                return params, {"source": "shm_raw", "version": v,
                                "load_s": time.monotonic() - t0}
            if raw_seen is not None:
                raw_seen["shm_raw"] = v
    got = load_raw_params(model_path)
    if got is not None:
        params, v = got
        if want_version is not None and v != want_version and raw_seen is not None:
            raw_seen["disk_raw"] = v
        # A mismatched disk raw still ends the chain: pickle/HF below
        # are version -1 (strictly less informative), and its intact
        # version lets the caller's retry loop wait for the right dump
        # and report exactly what it saw.
        return params, {"source": "disk_raw", "version": v,
                        "load_s": time.monotonic() - t0}
    if want_version is not None:
        # pickle/HF always report version -1: they can NEVER satisfy a
        # pinned version, so skip their multi-GB deserialization instead
        # of paying it once per retry while waiting for the raw dump.
        return None, {"source": "no_raw_dump", "version": -1,
                      "load_s": time.monotonic() - t0}
    state_file = os.path.join(model_path, "engine_state.pkl")
    if os.path.exists(state_file):
        import pickle

        with open(state_file, "rb") as f:
            params = pickle.load(f)["params"]
        return params, {"source": "pickle", "version": -1,
                        "load_s": time.monotonic() - t0}
    from areal_tpu.models.hf import load_hf_model

    _, params = load_hf_model(model_path)
    return params, {"source": "hf", "version": -1,
                    "load_s": time.monotonic() - t0}


def load_for_serving(
    model_path: str,
    shm_dir: Optional[str] = None,
    want_version: Optional[int] = None,
    retries: Optional[int] = None,
    retry_s: Optional[float] = None,
) -> Tuple[Any, Dict[str, Any]]:
    """Load params for a generation server's weight update, fastest source
    first. Returns (params, info) where info records the source and load
    seconds for the /metrics surface:

    1. ``shm_dir`` raw dump      — same-host tmpfs fast path
    2. ``model_path`` raw dump   — mmap from page cache / NFS
    3. ``model_path`` pickle     — engine_state.pkl (checkpoint fallback)
    4. ``model_path`` HF dir     — cold start from an HF checkpoint

    With ``want_version`` set, the loaded dump's version must MATCH it.
    The pickle/HF fallbacks report version -1 and a raw dump can lag the
    publisher; accepting either would pin stale weights under the new
    version label, silently corrupting routing and the staleness gate.
    The chain itself is version-aware: a raw dump holding the wrong
    version falls through to the next source (a stale tmpfs copy must
    not shadow the matching NFS dump). A miss is retried (the dump may
    still be landing — cross-host NFS attribute caching can lag the
    publisher by seconds, and a pinned retry is just a manifest read
    since it skips the pickle/HF deserialization), then raised as
    :class:`WeightVersionMismatch` so the caller fails the update and
    eviction/readmission re-syncs the server instead. The default
    budget (``AREAL_WEIGHT_LOAD_RETRIES`` x ``AREAL_WEIGHT_LOAD_RETRY_S``,
    40 x 0.25 s = 10 s) matches the plane path's manifest-retry scale.
    """
    t0 = time.monotonic()
    if retries is None:
        retries = int(os.environ.get("AREAL_WEIGHT_LOAD_RETRIES", "40"))
    if retry_s is None:
        retry_s = float(os.environ.get("AREAL_WEIGHT_LOAD_RETRY_S", "0.25"))
    attempts = max(1, retries)
    last_info = None
    raw_seen: Dict[str, int] = {}
    for attempt in range(attempts):
        params, info = _load_once(
            model_path, shm_dir, t0,
            want_version=want_version, raw_seen=raw_seen,
        )
        if want_version is None or info["version"] == want_version:
            return params, info
        last_info = info
        if attempt < attempts - 1:
            time.sleep(retry_s)
    raise WeightVersionMismatch(
        f"requested weight version {want_version} but "
        + (
            "no raw dump was available"
            if last_info["source"] == "no_raw_dump"
            else f"{last_info['source']} dump holds version "
            f"{last_info['version']}"
        )
        + f" after {attempts} attempt(s) (model_path={model_path}"
        + (f", mismatched raw dumps seen: {raw_seen}" if raw_seen else "")
        + ")"
    )

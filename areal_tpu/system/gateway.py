"""Multi-tenant streaming inference gateway (docs/serving.md).

The fleet's public front door: an OpenAI-compatible HTTP surface
(``POST /v1/completions``, ``POST /v1/chat/completions``, SSE token
streaming) that sits in front of the gserver manager and makes the
serving plane safely shareable by untrusted tenants:

- **auth**: Bearer API keys map to tenants (AREAL_GW_TENANTS); an
  unknown key is a clean 401, never a routed request;
- **model routing** (multi-model fleets): when AREAL_GW_MODELS names
  the fleet's model ids, the OpenAI ``"model"`` request field is
  resolved against them — an unknown model is a 404, a model the
  tenant is not entitled to (the optional 7th ``models=a|b`` tenant
  field) is a 403, and a resolved model rides the scheduling meta so
  the manager routes to that model's server pool only. Usage rows are
  metered per (tenant, model);
- **quotas**: each tenant owns a token bucket (tokens/s + burst) and a
  concurrent-stream cap. A request costing more than the tenant can
  afford is shed with 429 whose Retry-After is derived from the
  tenant's OWN bucket refill — never from fleet state, so one tenant's
  backoff schedule leaks nothing about another's traffic;
- **weighted fair share**: admitted requests queue per tenant and are
  dispatched by deficit round-robin weighted by tenant weight × the
  engine priority class (session continuations cost less, mirroring
  the engine's class-0 admission), so a noisy tenant saturating its
  quota cannot move a well-behaved tenant's p99 TTFT;
- **usage ledger**: per-tenant prompt/completion tokens, TTFT/ITL
  histograms (base/latency.py buckets) and sheds are journaled through
  an append-only usage WAL (system/wal.py, ``areal-gw-usage-wal/v1``)
  with per-request id dedup, so accounting is exactly-once across
  gateway SIGKILL + restart. Surfaced as ``areal:gw_*`` /metrics
  lines, the ``GET /v1/usage`` operator endpoint, and per-tenant rows
  in the manager's /status (via the gateway heartbeat payload);
- **house discipline**: the gateway→manager→server hop speaks the
  PR 14 contract — ``X-Areal-Deadline`` propagation, declared retry /
  breaker-report / shed-backoff policies (base/rpc.py), session
  affinity + ``kv_source`` hints preserved — and the whole request is
  a ``gateway.request`` trace span. Chaos points ``gw.auth`` and
  ``gw.shed`` arm via AREAL_FAULTS.

Internal trainer traffic is NOT a tenant like the others: rollout
workers opting in via AREAL_GW_TRAINER_VIA_GATEWAY route their
``/schedule_request`` hops through this gateway's trainer proxy, which
tags metas with the reserved ``trainer`` tenant, bypasses buckets and
queues entirely (weight ∞, never shed) and forwards to the manager
with the caller's deadline intact. The proxy — and the ``/v1/usage`` +
``/metrics`` operator surfaces — share the tenant-facing listener, so
they are gated by an INTERNAL TOKEN (AREAL_GW_INTERNAL_TOKEN, or a
random one minted at startup) published only through name_resolve
(``names.gateway_internal_token``): rollout workers and operators can
read it, external tenants cannot, and a caller without it gets a 401
instead of a free ride past auth, quotas, and metering. A tenant API
key on ``/v1/usage`` sees exactly its own row.

Prompts arrive as text (byte-level codec, exact for the vocab-256
harness models — api/public.py) or raw token-id lists; production
deployments inject a real tokenizer pair via the ``tokenizer`` hook.

TLS: AREAL_GW_TLS_CERT + AREAL_GW_TLS_KEY terminate TLS on the
tenant-facing listener (the published gateway URL becomes https://).
The production stance is mTLS at the load balancer with the gateway
behind it on a private network; the in-process terminator exists for
single-box deployments and the selftest's self-signed arm.
"""

from __future__ import annotations

import argparse
import asyncio
import collections
import hmac
import json
import math
import os
import ssl
import sys
import tempfile
import threading
import time
import uuid
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

import aiohttp

from areal_tpu.api import public
from areal_tpu.base import (
    env_registry,
    latency,
    logging,
    name_resolve,
    names,
    network,
    rpc,
    tracing,
)
from areal_tpu.base.fault_injection import faults
from areal_tpu.base.health import Heartbeat
from areal_tpu.base.wire_schemas import GATEWAY_V1, GW_USAGE_WAL_V1
from areal_tpu.system import model_registry
from areal_tpu.system.wal import RolloutWAL

logger = logging.getLogger("gateway")

# Reserved internal tenant: the training plane's own rollout traffic.
# Never declared in AREAL_GW_TENANTS, never shed, never queued — the
# trainer proxy tags scheduling metas with it so manager-side
# accounting and /status can attribute load, nothing more.
TRAINER_TENANT = "trainer"

# Header internal callers present on the trainer proxy and operator
# surfaces; the value is the gateway's internal token.
INTERNAL_TOKEN_HEADER = "X-Areal-Gateway-Token"


def resolve_gateway_once(
    experiment_name: str, trial_name: str
) -> Optional[Tuple[str, str]]:
    """One non-blocking discovery pass over the per-instance gateway
    records: returns (url, internal_token) of the lowest-id registered
    instance, or None while no gateway is up. Both records are written
    by the same instance at start(), so the pair is consistent."""
    try:
        keys = name_resolve.find_subtree(
            names.gateway_url_root(experiment_name, trial_name))
    except Exception:
        return None
    for key in sorted(keys):
        gid = key.rsplit("/", 1)[-1]
        try:
            url = name_resolve.get(
                names.gateway_url(experiment_name, trial_name, gid))
            token = name_resolve.get(
                names.gateway_internal_token(
                    experiment_name, trial_name, gid))
        except Exception:
            continue
        if url and token:
            return url, token
    return None


def discover_gateway(
    experiment_name: str, trial_name: str, timeout: float = 300.0
) -> Tuple[str, str]:
    """Block until some gateway instance registers; returns
    (url, internal_token). The trainer-via-gateway rollout path's
    counterpart of name_resolve.wait on the manager key."""
    deadline = time.monotonic() + timeout
    while True:
        got = resolve_gateway_once(experiment_name, trial_name)
        if got is not None:
            return got
        if time.monotonic() >= deadline:
            raise TimeoutError(
                f"no gateway registered for "
                f"{experiment_name}/{trial_name} within {timeout:.0f}s"
            )
        time.sleep(0.5)


class Tenant:
    """One paying tenant: identity + quota state.

    Bucket/stream state is only ever touched from the gateway's single
    HTTP event loop, so no locking. ``level`` refills continuously at
    ``tokens_per_s`` up to ``burst``; a request charges
    prompt_len + max_tokens units up front (the worst case it may
    consume — billing afterwards is by actual emission, the bucket is
    purely admission control)."""

    def __init__(self, name: str, api_key: str, weight: float,
                 tokens_per_s: float, burst: float, max_streams: int,
                 models: Optional[frozenset] = None):
        self.name = name
        self.api_key = api_key
        self.weight = float(weight)
        self.tokens_per_s = float(tokens_per_s)
        self.burst = float(burst)
        self.max_streams = int(max_streams)
        # Model entitlements: None = every model the fleet serves;
        # a frozenset restricts the tenant to exactly those model ids
        # (anything else answers 403, never a cross-model route).
        self.models = models
        self.level = float(burst)
        self.stamp = time.monotonic()
        self.active_streams = 0

    def _refill(self, now: float):
        self.level = min(
            self.burst,
            self.level + max(0.0, now - self.stamp) * self.tokens_per_s,
        )
        self.stamp = now

    def time_to_afford(self, cost: float, now: float) -> float:
        """Seconds until THIS tenant's bucket can pay ``cost`` (0.0 if
        it already can). The 429 Retry-After source."""
        self._refill(now)
        if self.level >= cost:
            return 0.0
        if self.tokens_per_s <= 0:
            return 3600.0
        return (cost - self.level) / self.tokens_per_s

    def try_charge(self, cost: float, now: float) -> Optional[float]:
        """Charge the bucket; None on success, else the tenant's own
        seconds-until-affordable (the Retry-After)."""
        wait = self.time_to_afford(cost, now)
        if wait <= 0.0:
            self.level -= cost
            return None
        return wait


def parse_tenant_spec(
    spec: Optional[str],
    known_models: Optional[set] = None,
) -> Dict[str, Tenant]:
    """Parse AREAL_GW_TENANTS: comma list of
    ``name:api_key:weight:tokens_per_s:burst:max_streams`` entries,
    optionally followed by a 7th ``model|model`` entitlement field
    (absent = entitled to every model the fleet serves).
    Raises ValueError on malformed entries, duplicate names, duplicate
    API keys (a shared key would silently bill whichever tenant parses
    last), non-positive quotas, an attempt to redeclare the reserved
    trainer tenant, or — when ``known_models`` is given — an
    entitlement naming a model the fleet does not serve (a typo here
    would silently lock the tenant out or grant nothing; fail at parse
    time instead)."""
    tenants: Dict[str, Tenant] = {}
    keys_seen: Dict[str, str] = {}
    if not spec:
        return tenants
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) not in (6, 7):
            raise ValueError(
                f"bad tenant entry {entry!r}: want "
                f"name:api_key:weight:tokens_per_s:burst:max_streams"
                f"[:model|model...]"
            )
        name, api_key, weight, rate, burst, streams = parts[:6]
        entitled: Optional[frozenset] = None
        if len(parts) == 7 and parts[6].strip():
            models = [m.strip() for m in parts[6].split("|")
                      if m.strip()]
            for m in models:
                model_registry.validate_model_id(m)
                if known_models is not None and m not in known_models:
                    raise ValueError(
                        f"tenant {name!r} entitlement names unknown "
                        f"model {m!r} (fleet serves "
                        f"{sorted(known_models)})"
                    )
            entitled = frozenset(models)
        if not name or not api_key:
            raise ValueError(f"tenant entry {entry!r}: empty name or key")
        if name == TRAINER_TENANT:
            raise ValueError(
                f"tenant name {TRAINER_TENANT!r} is reserved for the "
                f"training plane and may not be declared"
            )
        if name in tenants:
            raise ValueError(f"duplicate tenant {name!r}")
        if api_key in keys_seen:
            raise ValueError(
                f"tenant {name!r} reuses the API key of tenant "
                f"{keys_seen[api_key]!r}: keys must be unique or "
                f"auth cannot attribute traffic"
            )
        keys_seen[api_key] = name
        t = Tenant(name, api_key, float(weight), float(rate),
                   float(burst), int(streams), models=entitled)
        if t.weight <= 0 or t.tokens_per_s <= 0 or t.burst <= 0 \
                or t.max_streams < 1:
            raise ValueError(
                f"tenant {name!r}: weight/tokens_per_s/burst must be "
                f"> 0 and max_streams >= 1"
            )
        tenants[name] = t
    return tenants


class UsageLedger:
    """Exactly-once per-tenant usage accounting over a usage WAL.

    Every billable event (a served request's token counts + latency
    histograms, or a shed) is journaled with a unique request id BEFORE
    it lands in the in-memory rows; restart replays the journal through
    the same ``_apply`` with rid dedup, so a record is counted exactly
    once no matter how many times the gateway dies and replays.
    Thread-safe: the HTTP loop journals through run_in_executor while
    the supervisor thread reads briefs.

    A long-lived gateway must not grow without bound: every
    AREAL_GW_USAGE_COMPACT_EVERY journaled records the WAL is folded
    into ONE aggregated per-tenant ``agg`` record (RolloutWAL.compact —
    the totals the individual records sum to, so a restart replays the
    aggregate plus whatever landed after it into identical rows), and
    the request-id dedup set is aged down to a bounded recent window.
    Disk, replay time, and dedup memory are all O(cadence), not
    O(lifetime traffic)."""

    # Request ids kept for duplicate defence across a compaction; only
    # in-flight retries can legitimately re-present a rid, so a short
    # recency window is enough.
    SEEN_WINDOW = 1024

    def __init__(self, path: str, compact_every: Optional[int] = None):
        self._lock = threading.Lock()
        self._wal = RolloutWAL(path, schema=GW_USAGE_WAL_V1)
        self._seen: set = set()
        self._recent: Deque[str] = collections.deque(
            maxlen=self.SEEN_WINDOW)
        if compact_every is None:
            compact_every = env_registry.get_int(
                "AREAL_GW_USAGE_COMPACT_EVERY")
        self._compact_every = max(0, int(compact_every))
        self._records = 0  # journal records since the last compaction
        # Rows are keyed (tenant, model); model "" is single-model
        # traffic (and every pre-multi-model WAL record replays there).
        self._rows: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self.replayed = 0
        self.dup_dropped = 0
        self.compactions = 0
        for rec in self._wal.replay():
            if self._apply(rec):
                self.replayed += 1
                self._records += 1
            else:
                self.dup_dropped += 1
        # A journal that replayed past the cadence (e.g. a crash loop)
        # compacts immediately instead of carrying the backlog forward.
        self._maybe_compact_locked()

    def _row(self, tenant: str, model: str = "") -> Dict[str, Any]:
        row = self._rows.get((tenant, model))
        if row is None:
            row = {
                "requests": 0,
                "sheds": 0,
                "prompt_tokens": 0,
                "completion_tokens": 0,
                "ttft_counts": [0] * latency.N_BUCKETS,
                "itl_counts": [0] * latency.N_BUCKETS,
            }
            self._rows[(tenant, model)] = row
        return row

    def _apply(self, rec: Dict[str, Any]) -> bool:
        rid = rec.get("rid")
        if not rid or rid in self._seen:
            return False
        self._seen.add(rid)
        self._recent.append(rid)
        if rec.get("kind") == "agg":
            # A compaction record: the summed totals of every
            # individual record it replaced, added wholesale. Keys are
            # "tenant" or "tenant\tmodel" (pre-multi-model aggregates
            # have no tab and land on the "" model row).
            for rkey, agg in (rec.get("rows") or {}).items():
                tenant, _, model = str(rkey).partition("\t")
                row = self._row(tenant, model)
                for k in ("requests", "sheds", "prompt_tokens",
                          "completion_tokens"):
                    row[k] += int(agg.get(k) or 0)
                for key in ("ttft_counts", "itl_counts"):
                    for i, n in enumerate(
                        latency.decode_counts(agg.get(key) or "")
                    ):
                        row[key][i] += n
            return True
        row = self._row(str(rec.get("tenant") or "unknown"),
                        str(rec.get("model") or ""))
        if rec.get("kind") == "shed":
            row["sheds"] += 1
            return True
        row["requests"] += 1
        row["prompt_tokens"] += int(rec.get("prompt_tokens") or 0)
        row["completion_tokens"] += int(rec.get("completion_tokens") or 0)
        if rec.get("ttft_ms") is not None:
            row["ttft_counts"][
                latency.bucket_index(float(rec["ttft_ms"]))
            ] += 1
        itl = latency.decode_counts(rec.get("itl_counts") or "")
        for i, n in enumerate(itl):
            row["itl_counts"][i] += n
        return True

    def _maybe_compact_locked(self):
        """Compact once the cadence is reached; caller holds the lock
        (or is the single-threaded constructor)."""
        if self._compact_every <= 0 \
                or self._records < self._compact_every:
            return
        agg_rid = "agg-" + uuid.uuid4().hex
        rec = {
            "rid": agg_rid,
            "kind": "agg",
            "ts": time.time(),
            "rows": {
                (name if not model else f"{name}\t{model}"): {
                    "requests": r["requests"],
                    "sheds": r["sheds"],
                    "prompt_tokens": r["prompt_tokens"],
                    "completion_tokens": r["completion_tokens"],
                    "ttft_counts": latency.encode_counts(
                        r["ttft_counts"]),
                    "itl_counts": latency.encode_counts(
                        r["itl_counts"]),
                }
                for (name, model), r in self._rows.items()
            },
        }
        # The aggregate IS the current in-memory rows (every applied
        # record was journaled first), so it is NOT re-applied here —
        # it exists purely for the next replay. Append, fsync, then
        # drop everything else: the journal becomes [agg].
        self._wal.append(rec)
        self._wal.sync()
        dropped = self._wal.compact(
            lambda r: r.get("rid") == agg_rid)
        # Age the dedup set down to the recent window (+ the aggregate
        # itself): only in-flight duplicates need defending against.
        self._seen = set(self._recent)
        self._seen.add(agg_rid)
        self._records = 1  # the agg record itself
        self.compactions += 1
        logger.info(
            f"usage WAL compacted: {dropped} records folded into one "
            f"aggregate ({len(rec['rows'])} tenants)"
        )

    def record_usage(self, rid: str, tenant: str, prompt_tokens: int,
                     completion_tokens: int, ttft_ms: Optional[float],
                     itl_counts: Optional[List[int]],
                     model: str = "") -> bool:
        """Journal + count one served request. fsyncs before counting:
        a record is billed iff it is durable (SIGKILL right after the
        response leaves at most the terminal frame unbilled, never a
        double-count)."""
        rec = {
            "rid": rid,
            "kind": "usage",
            "tenant": tenant,
            "model": model,
            "prompt_tokens": int(prompt_tokens),
            "completion_tokens": int(completion_tokens),
            "ttft_ms": None if ttft_ms is None else float(ttft_ms),
            "itl_counts": latency.encode_counts(itl_counts or []),
            "ts": time.time(),
        }
        with self._lock:
            if rid in self._seen:
                self.dup_dropped += 1
                return False
            self._wal.append(rec)
            self._wal.sync()
            applied = self._apply(rec)
            self._records += 1
            self._maybe_compact_locked()
            return applied

    def record_shed(self, rid: str, tenant: str,
                    model: str = "") -> bool:
        rec = {"rid": rid, "kind": "shed", "tenant": tenant,
               "model": model, "ts": time.time()}
        with self._lock:
            if rid in self._seen:
                self.dup_dropped += 1
                return False
            self._wal.append(rec)
            self._wal.sync()
            applied = self._apply(rec)
            self._records += 1
            self._maybe_compact_locked()
            return applied

    def totals(self) -> Tuple[int, int, List[int], List[int]]:
        """(prompt_tokens, completion_tokens, merged ttft counts,
        merged itl counts) across all tenants — the /metrics source."""
        with self._lock:
            rows = list(self._rows.values())
        pt = sum(r["prompt_tokens"] for r in rows)
        ct = sum(r["completion_tokens"] for r in rows)
        ttft = latency.merge_counts([r["ttft_counts"] for r in rows])
        itl = latency.merge_counts([r["itl_counts"] for r in rows])
        return pt, ct, ttft, itl

    @staticmethod
    def _present(requests: int, sheds: int, pt: int, ct: int,
                 ttft: List[int], itl: List[int]) -> Dict[str, Any]:
        return {
            "requests": requests,
            "sheds": sheds,
            "prompt_tokens": pt,
            "completion_tokens": ct,
            "total_tokens": pt + ct,
            "ttft_p50_ms": latency.percentile_from_counts(ttft, 50.0),
            "ttft_p99_ms": latency.percentile_from_counts(ttft, 99.0),
            "itl_p50_ms": latency.percentile_from_counts(itl, 50.0),
            "itl_p99_ms": latency.percentile_from_counts(itl, 99.0),
        }

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Per-tenant rows with computed percentiles (GET /v1/usage).
        The top-level tenant row aggregates across models (ratio-of-
        sums over merged histogram counts); per-model sub-rows sit
        under ``"models"`` keyed by model id. Single-model traffic
        (model "") contributes only to the aggregate."""
        out: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            by_tenant: Dict[str, List[Tuple[str, Dict[str, Any]]]] = {}
            for (name, model), r in self._rows.items():
                by_tenant.setdefault(name, []).append((model, r))
            for name, rows in by_tenant.items():
                agg = self._present(
                    sum(r["requests"] for _, r in rows),
                    sum(r["sheds"] for _, r in rows),
                    sum(r["prompt_tokens"] for _, r in rows),
                    sum(r["completion_tokens"] for _, r in rows),
                    latency.merge_counts(
                        [r["ttft_counts"] for _, r in rows]),
                    latency.merge_counts(
                        [r["itl_counts"] for _, r in rows]),
                )
                models = {
                    model: self._present(
                        r["requests"], r["sheds"],
                        r["prompt_tokens"], r["completion_tokens"],
                        r["ttft_counts"], r["itl_counts"])
                    for model, r in rows if model
                }
                if models:
                    agg["models"] = models
                out[name] = agg
        return out

    def brief(self) -> Dict[str, Dict[str, int]]:
        """Compact totals for the heartbeat payload (manager /status).
        Aggregated across models — the wire shape predates the
        multi-model plane and /status consumers sum rows anyway."""
        with self._lock:
            out: Dict[str, Dict[str, int]] = {}
            for (n, _model), r in self._rows.items():
                b = out.setdefault(n, {
                    "requests": 0, "sheds": 0,
                    "prompt_tokens": 0, "completion_tokens": 0,
                })
                for k in b:
                    b[k] += r[k]
            return out

    def close(self):
        with self._lock:
            self._wal.close()


class _QueueItem:
    """One admitted request waiting for a fair-share dispatch slot."""

    __slots__ = ("tenant", "cost", "fut")

    def __init__(self, tenant: str, cost: float, fut: asyncio.Future):
        self.tenant = tenant
        self.cost = cost
        self.fut = fut


class _ServerFailure(RuntimeError):
    def __init__(self, url: str, detail: str):
        super().__init__(f"generate failed on {url}: {detail}")
        self.url = url


class GatewayService:
    """The gateway process: HTTP front + DRR dispatcher + usage ledger
    + health lease (lifecycle mirrors system/reward_executor.py)."""

    def __init__(
        self,
        experiment_name: str,
        trial_name: str,
        gateway_id: int = 0,
        port: int = 0,
        manager_addr: Optional[str] = None,
        tenant_spec: Optional[str] = None,
        usage_wal_path: Optional[str] = None,
        fair_share: Optional[bool] = None,
        tokenizer: Optional[Tuple[Callable, Callable]] = None,
        internal_token: Optional[str] = None,
        model_spec: Optional[str] = None,
    ):
        self.experiment_name = experiment_name
        self.trial_name = trial_name
        self.gateway_id = int(gateway_id)
        self.member = f"gateway/{self.gateway_id}"
        self.manager_addr = manager_addr
        self.request_timeout = env_registry.get_float(
            "AREAL_GW_REQUEST_TIMEOUT_S")
        self.chunk_tokens = max(1, env_registry.get_int(
            "AREAL_GW_CHUNK_TOKENS"))
        self.max_inflight = max(1, env_registry.get_int(
            "AREAL_GW_MAX_INFLIGHT"))
        self.retry_after_floor = env_registry.get_float(
            "AREAL_GW_RETRY_AFTER_FLOOR_S")
        self.fair_share = (
            fair_share if fair_share is not None
            else env_registry.get_bool("AREAL_GW_FAIR_SHARE")
        )
        # Multi-model serving: AREAL_GW_MODELS (or the explicit arg)
        # names the model ids the fleet serves; the first entry is the
        # default a request without a meaningful "model" field maps
        # to. Empty = single-model legacy mode (no model resolution,
        # no model tag on the scheduling meta).
        mspec = (model_spec if model_spec is not None
                 else env_registry.get_str("AREAL_GW_MODELS"))
        self.models: List[str] = []
        for m in (mspec or "").split(","):
            m = m.strip()
            if m and m not in self.models:
                model_registry.validate_model_id(m)
                self.models.append(m)
        self._known_models = set(self.models)
        self.default_model = self.models[0] if self.models else None
        spec = (tenant_spec if tenant_spec is not None
                else env_registry.get_str("AREAL_GW_TENANTS"))
        self.tenants = parse_tenant_spec(
            spec,
            known_models=self._known_models or None,
        )
        self._by_key = {t.api_key: t for t in self.tenants.values()}
        # Internal-surface shared secret (trainer proxy + operator
        # endpoints): explicit arg > env knob > random mint. Published
        # to name_resolve at start() — reachable by rollout workers
        # and operators, never by external tenants.
        if internal_token is None:
            internal_token = env_registry.get_str(
                "AREAL_GW_INTERNAL_TOKEN")
        self.internal_token = internal_token or uuid.uuid4().hex
        # Optional (encode(text)->ids, decode(ids)->text) pair; absent,
        # api/public.py's byte codec applies.
        self.tokenizer = tokenizer
        # TLS termination (AREAL_GW_TLS_CERT/KEY): both knobs set ->
        # the tenant listener serves https and the published discovery
        # URL says so. Production fleets usually terminate mTLS at the
        # load balancer instead (docs/serving.md); exactly one knob
        # set is a config error, not a silent plaintext listener.
        self._tls_cert = env_registry.get_str("AREAL_GW_TLS_CERT")
        self._tls_key = env_registry.get_str("AREAL_GW_TLS_KEY")
        if bool(self._tls_cert) != bool(self._tls_key):
            raise ValueError(
                "AREAL_GW_TLS_CERT and AREAL_GW_TLS_KEY must be set "
                "together (got exactly one)"
            )
        if usage_wal_path is None:
            usage_wal_path = os.path.join(
                tempfile.gettempdir(),
                f"areal_gw_usage_{experiment_name}_{trial_name}"
                f"_{self.gateway_id}.jsonl",
            )
        self.ledger = UsageLedger(usage_wal_path)
        # Declared retry disciplines (base/rpc.py): per-request server
        # failover budget, plus the fleet-wide manager-rediscovery one.
        self._policy = rpc.default_policy(
            attempt_timeout_s=self.request_timeout)
        self._mgr_policy = rpc.rediscovery_policy()
        self.counters: Dict[str, int] = {
            "requests_total": 0,
            "auth_failures_total": 0,
            "shed_total": 0,
            "fairshare_picks_total": 0,
            "upstream_failovers_total": 0,
            "model_rejections_total": 0,
        }
        self._trainer_sched = 0
        # DRR state (event-loop confined).
        self.quantum = 64.0
        self._queues: Dict[str, Deque[_QueueItem]] = {}
        self._fifo: Deque[_QueueItem] = collections.deque()
        self._rr: List[str] = []
        self._deficit: Dict[str, float] = {}
        self._inflight = 0
        self._queue_event: Optional[asyncio.Event] = None
        self._dispatch_task: Optional[asyncio.Task] = None
        self._session: Optional[aiohttp.ClientSession] = None
        self._port = port
        self.address: Optional[str] = None
        self._heartbeat: Optional[Heartbeat] = None
        self._http_loop: Optional[asyncio.AbstractEventLoop] = None
        self._http_ready = threading.Event()
        self._http_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._sup_thread: Optional[threading.Thread] = None
        faults.set_scope(self.member)

    # -- manager discovery ---------------------------------------------

    def _refresh_manager_addr(self):
        """Blocking name_resolve lookup — call via run_in_executor from
        async paths."""
        try:
            addr = name_resolve.get(
                names.gen_server_manager(
                    self.experiment_name, self.trial_name)
            )
        except Exception:
            return
        if addr and addr != self.manager_addr:
            logger.warning(
                f"gserver manager moved {self.manager_addr} -> {addr}"
            )
            self.manager_addr = addr

    async def _sess(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=self.request_timeout)
            )
        return self._session

    # -- fair-share dispatcher -----------------------------------------

    def _enqueue(self, item: _QueueItem):
        if self.fair_share:
            q = self._queues.get(item.tenant)
            if q is None:
                q = self._queues[item.tenant] = collections.deque()
                if item.tenant not in self._rr:
                    self._rr.append(item.tenant)
            q.append(item)
        else:
            self._fifo.append(item)
        if self._queue_event is not None:
            self._queue_event.set()

    def _release_slot(self):
        self._inflight = max(0, self._inflight - 1)
        if self._queue_event is not None:
            self._queue_event.set()

    def _queue_depth(self) -> int:
        return len(self._fifo) + sum(
            len(q) for q in self._queues.values())

    def _dispatch_one(self) -> bool:
        """Pick and release one queued request. Exact weighted DRR: all
        nonempty queues advance their deficit by the minimum number of
        rounds that makes some head affordable, then that head is
        served — O(tenants) per dispatch, no credit-spin loop."""
        if self._inflight >= self.max_inflight:
            return False
        if not self.fair_share:
            while self._fifo and self._fifo[0].fut.cancelled():
                self._fifo.popleft()
            if not self._fifo:
                return False
            item = self._fifo.popleft()
            self._inflight += 1
            item.fut.set_result(True)
            return True
        nonempty: List[str] = []
        for name in list(self._rr):
            q = self._queues.get(name)
            while q and q[0].fut.cancelled():
                q.popleft()
            if q:
                nonempty.append(name)
            else:
                # Classic DRR: an emptied queue forfeits its credit.
                self._deficit[name] = 0.0
        if not nonempty:
            return False
        if len(nonempty) > 1:
            # Proof the queue actually arbitrated between tenants (the
            # tenant_fairness bench validator keys on this moving).
            self.counters["fairshare_picks_total"] += 1
        best: Optional[str] = None
        best_rounds = 0
        for name in nonempty:
            t = self.tenants.get(name)
            weight = t.weight if t is not None else 1.0
            credit = self.quantum * max(1e-6, weight)
            need = (self._queues[name][0].cost
                    - self._deficit.get(name, 0.0))
            rounds = 0 if need <= 0 else int(math.ceil(need / credit))
            if best is None or rounds < best_rounds:
                best, best_rounds = name, rounds
        if best_rounds > 0:
            for name in nonempty:
                t = self.tenants.get(name)
                weight = t.weight if t is not None else 1.0
                self._deficit[name] = (
                    self._deficit.get(name, 0.0)
                    + best_rounds * self.quantum * max(1e-6, weight)
                )
        item = self._queues[best].popleft()
        self._deficit[best] = max(
            0.0, self._deficit.get(best, 0.0) - item.cost)
        # Served tenant rotates to the back (round-robin tie order).
        try:
            self._rr.remove(best)
            self._rr.append(best)
        except ValueError:
            pass
        self._inflight += 1
        item.fut.set_result(True)
        return True

    async def _dispatch_loop(self):
        while True:
            await self._queue_event.wait()
            self._queue_event.clear()
            while self._dispatch_one():
                pass

    # -- upstream generation -------------------------------------------

    def _model_tag(self, parsed: public.ParsedRequest) -> str:
        """Ledger/meta model id: the resolved model in multi-model
        mode, "" in single-model legacy mode (where parsed.model is
        whatever placeholder the client sent and must not be routed
        or billed as a pool name)."""
        return parsed.model if self._known_models else ""

    async def _schedule(self, meta: Dict[str, Any]) -> Dict[str, Any]:
        sess = await self._sess()
        dl = rpc.Deadline.after(self.request_timeout)
        async with sess.post(
            f"{self.manager_addr}/schedule_request", json=meta,
            headers=dl.headers(),
        ) as r:
            return await r.json()

    async def _generate_chunks(
        self,
        parsed: public.ParsedRequest,
        tenant: Tenant,
        qid: str,
        deadline: rpc.Deadline,
        t_start: float,
        emit,
    ) -> Tuple[List[int], str, Optional[float], List[int], Optional[str]]:
        """Drive one request through the manager chunk by chunk
        (partial_rollout discipline: failover via failed_server_url,
        shed hints, manager rediscovery, session affinity on qid).
        Calls ``await emit(token_ids)`` per successful chunk. Returns
        (output_ids, finish_reason, ttft_ms, itl_counts, error_detail)
        — never raises for upstream exhaustion, so the caller can bill
        what was actually emitted."""
        sess = await self._sess()
        loop = asyncio.get_event_loop()
        acc: List[int] = []
        prev_url, prev_version = "", -1
        failed_url: Optional[str] = None
        shed_url: Optional[str] = None
        shed_ra_hint = 0.0
        retries = 0
        consec_shed = 0
        n_shed = 0
        shed_budget = max(32, self._policy.attempts * 8)
        mgr_fails = 0
        consec_mgr = 0
        ttft_ms: Optional[float] = None
        itl_counts = [0] * latency.N_BUCKETS
        t_last = t_start
        finish = "length"
        error: Optional[str] = None
        budget = parsed.max_tokens
        while budget > 0:
            if deadline.expired():
                break
            meta = tracing.inject_into(dict(
                qid=qid,
                prompt_len=len(parsed.prompt_ids) + len(acc),
                group_size=1,
                new_token_budget=budget,
                previous_server_url=prev_url,
                previous_version=prev_version,
                failed_server_url=failed_url,
                shed_server_url=shed_url,
                shed_retry_after=shed_ra_hint,
                tenant=tenant.name,
                model=self._model_tag(parsed),
            ))
            try:
                sched = await self._schedule(meta)
            except (aiohttp.ClientError, asyncio.TimeoutError) as e:
                mgr_fails += 1
                consec_mgr += 1
                if mgr_fails > self._mgr_policy.attempts:
                    error = (f"gserver manager unreachable after "
                             f"{mgr_fails} attempts: {e!r}")
                    finish = "error"
                    break
                await loop.run_in_executor(
                    None, self._refresh_manager_addr)
                await asyncio.sleep(self._mgr_policy.backoff(consec_mgr))
                continue
            consec_mgr = 0
            failed_url = None
            shed_url, shed_ra_hint = None, 0.0
            if "url" not in sched:
                retries += 1
                if retries > self._policy.attempts:
                    error = f"no healthy generation servers: {sched}"
                    finish = "error"
                    break
                await asyncio.sleep(self._policy.backoff(
                    retries,
                    retry_after=float(sched.get("retry_after", 0.0)),
                ))
                continue
            url = sched["url"]
            chunk = min(budget, self.chunk_tokens)
            payload = tracing.inject_into(dict(
                qid=qid,
                decode_url=sched.get("decode_url"),
                kv_source=sched.get("kv_source"),
                input_ids=list(parsed.prompt_ids) + acc,
                # Continuations (accumulated prefix or an explicit
                # session) ride engine priority class 0 — same rule as
                # the trainer's partial-rollout client.
                priority=0 if (acc or parsed.session) else 1,
                gconfig=dict(
                    max_new_tokens=chunk,
                    min_new_tokens=0,
                    greedy=parsed.greedy,
                    temperature=parsed.temperature,
                    top_p=parsed.top_p,
                    top_k=-1,
                    stop_token_ids=[],
                ),
            ))
            shed_ra: Optional[float] = None
            try:
                chunk_dl = rpc.Deadline.after(min(
                    self.request_timeout,
                    max(rpc.MIN_ATTEMPT_S, deadline.remaining()),
                ))
                async with sess.post(
                    f"{url}/generate", json=payload,
                    headers=chunk_dl.headers(),
                ) as r:
                    if r.status == 429:
                        try:
                            body = await r.json()
                        except Exception:
                            body = {}
                        shed_ra = float(
                            body.get("retry_after")
                            or r.headers.get("Retry-After")
                            or 1.0
                        )
                    elif r.status != 200:
                        raise _ServerFailure(
                            url, f"{r.status} {await r.text()}")
                    else:
                        out = await r.json()
            except (_ServerFailure, aiohttp.ClientError,
                    asyncio.TimeoutError) as e:
                # Server died mid-chunk: tokens already emitted to the
                # client are safe in acc — the retry resubmits the full
                # prefix through the manager, which routes around the
                # failure. No token is ever emitted (or billed) twice.
                retries += 1
                self.counters["upstream_failovers_total"] += 1
                if retries > self._policy.attempts:
                    error = f"upstream exhausted: {e!r}"
                    finish = "error"
                    break
                failed_url = url
                prev_url, prev_version = "", -1
                logger.warning(
                    f"{qid}: generate failed on {url} ({e!r}); "
                    f"retry {retries}/{self._policy.attempts}"
                )
                await asyncio.sleep(self._policy.backoff(retries))
                continue
            if shed_ra is not None:
                n_shed += 1
                consec_shed += 1
                if n_shed > shed_budget:
                    error = (f"load-shed {n_shed} times; fleet "
                             f"persistently overloaded")
                    finish = "error"
                    break
                shed_url, shed_ra_hint = url, shed_ra
                await asyncio.sleep(
                    rpc.shed_backoff(consec_shed, shed_ra))
                continue
            consec_shed = 0
            toks = [int(t) for t in out.get("output_ids") or []]
            now = time.monotonic()
            if toks:
                if ttft_ms is None:
                    ttft_ms = (now - t_start) * 1000.0
                else:
                    per_tok = (now - t_last) * 1000.0 / len(toks)
                    itl_counts[latency.bucket_index(per_tok)] += len(toks)
            t_last = now
            made_progress = bool(toks)
            acc.extend(toks)
            budget = parsed.max_tokens - len(acc)
            prev_url = url
            prev_version = int(out.get("version_end", -1))
            if toks:
                await emit(toks)
            if not out.get("no_eos", True):
                finish = "stop"
                break
            if not made_progress and not out.get("interrupted", False):
                break
            if budget <= 0:
                break
        return acc, finish, ttft_ms, itl_counts, error

    # -- public handlers -----------------------------------------------

    async def _h_completions(self, request):
        return await self._serve_public(request, "completion")

    async def _h_chat(self, request):
        return await self._serve_public(request, "chat")

    async def _serve_public(self, request, kind: str):
        from aiohttp import web

        self.counters["requests_total"] += 1
        # Auth: a key-store flake (chaos gw.auth) must surface as a
        # clean 401-class refusal, never a routed request or a 500.
        tenant: Optional[Tenant] = None
        try:
            faults.maybe_fail("gw.auth")
            auth = request.headers.get("Authorization", "")
            key = auth[7:] if auth.startswith("Bearer ") else auth
            tenant = self._by_key.get(key)
        except Exception as e:
            logger.warning(f"auth path failed: {e!r}")
            tenant = None
        if tenant is None:
            self.counters["auth_failures_total"] += 1
            return web.json_response(
                public.error_body(401, "invalid or missing API key"),
                status=401,
            )
        try:
            body = await request.json()
            parsed = (public.parse_completion_request(body)
                      if kind == "completion"
                      else public.parse_chat_request(body))
        except public.PublicApiError as e:
            return web.json_response(
                public.error_body(e.status, e.message), status=e.status)
        except Exception:
            return web.json_response(
                public.error_body(400, "malformed JSON body"),
                status=400,
            )
        if self._known_models:
            # Multi-model resolution: the OpenAI "model" field picks
            # the pool. "areal" is api/public.py's absent-field
            # placeholder, so it (like "") maps to the default model;
            # anything else must name a served model (404) the tenant
            # is entitled to (403). The resolved id rides the
            # scheduling meta — a wrong-pool route is the manager's
            # error to refuse, never a silent cross-model hit.
            requested = parsed.model
            if requested in ("", "areal"):
                requested = self.default_model
            if requested not in self._known_models:
                self.counters["model_rejections_total"] += 1
                return web.json_response(
                    public.error_body(
                        404, f"unknown model {requested!r}"),
                    status=404,
                )
            if tenant.models is not None \
                    and requested not in tenant.models:
                self.counters["model_rejections_total"] += 1
                return web.json_response(
                    public.error_body(
                        403,
                        f"tenant {tenant.name} is not entitled to "
                        f"model {requested!r}",
                    ),
                    status=403,
                )
            parsed.model = requested
        inbound = rpc.Deadline.from_headers(request.headers)
        if inbound is not None and inbound.expired():
            return web.json_response(
                public.error_body(429, "deadline expired",
                                  retry_after=0.0),
                status=429, headers={"Retry-After": "0"},
            )
        deadline = rpc.ensure_deadline(inbound, self.request_timeout)
        prefix = "cmpl-" if kind == "completion" else "chatcmpl-"
        rid = prefix + uuid.uuid4().hex
        # Admission cost: the worst case this request may consume.
        # Session continuations discount to the engine's class-0 rate —
        # their prefix KV is already paid for.
        cost = (len(parsed.prompt_ids) + parsed.max_tokens) * (
            0.5 if parsed.session else 1.0)
        # Chaos gw.shed fires BEFORE the bucket charge: a crash inside
        # the shed decision must never leak a charge or a ledger row.
        faults.maybe_fail("gw.shed")
        now = time.monotonic()
        loop = asyncio.get_event_loop()
        if tenant.active_streams >= tenant.max_streams:
            retry_after: Optional[float] = tenant.time_to_afford(
                cost, now)
        else:
            retry_after = tenant.try_charge(cost, now)
        if retry_after is not None:
            self.counters["shed_total"] += 1
            ra = max(self.retry_after_floor, retry_after)

            def _journal_shed():
                self.ledger.record_shed(
                    rid, tenant.name, model=self._model_tag(parsed))

            await loop.run_in_executor(None, _journal_shed)
            return web.json_response(
                public.error_body(
                    429,
                    f"tenant {tenant.name} over quota "
                    f"(streams {tenant.active_streams}/"
                    f"{tenant.max_streams})",
                    retry_after=ra,
                ),
                status=429, headers={"Retry-After": f"{ra:.3f}"},
            )
        item = _QueueItem(tenant.name, cost, loop.create_future())
        self._enqueue(item)
        tenant.active_streams += 1
        try:
            await item.fut
            with tracing.span(
                "gateway.request", rid=rid, tenant=tenant.name,
                kind=kind, prompt_len=len(parsed.prompt_ids),
            ):
                # TTFT is admission-to-first-token: `now` predates the
                # fair-share queue wait, so the per-tenant histograms
                # actually witness queueing unfairness (a queue-blind
                # clock would make the fairness evidence vacuous).
                return await self._reply(request, parsed, tenant, rid,
                                         deadline, t_start=now)
        finally:
            tenant.active_streams -= 1
            if item.fut.done() and not item.fut.cancelled():
                self._release_slot()

    async def _reply(self, request, parsed: public.ParsedRequest,
                     tenant: Tenant, rid: str, deadline: rpc.Deadline,
                     t_start: Optional[float] = None):
        from aiohttp import web

        qid = f"gw/{tenant.name}/{parsed.session or rid}"
        if t_start is None:
            t_start = time.monotonic()
        loop = asyncio.get_event_loop()
        resp: Optional[web.StreamResponse] = None
        first_box = [True]

        async def emit(toks: List[int]):
            nonlocal resp
            if not parsed.stream:
                return
            if resp is None:
                resp = web.StreamResponse()
                resp.headers["Content-Type"] = "text/event-stream"
                resp.headers["Cache-Control"] = "no-cache"
                await resp.prepare(request)
            chunk = (
                public.completion_chunk(rid, parsed.model, toks)
                if parsed.kind == "completion"
                else public.chat_chunk(rid, parsed.model, toks,
                                       first=first_box[0])
            )
            first_box[0] = False
            await resp.write(public.sse_event(chunk))

        acc, finish, ttft_ms, itl_counts, error = \
            await self._generate_chunks(
                parsed, tenant, qid, deadline, t_start, emit)
        billable = bool(acc) or error is None
        if billable:
            # Journal BEFORE the terminal frame: billed-as-emitted.
            # A mid-stream failover already resumed from the emitted
            # prefix, so len(acc) is exactly what the client received.
            def _journal():
                self.ledger.record_usage(
                    rid, tenant.name, len(parsed.prompt_ids), len(acc),
                    ttft_ms, itl_counts,
                    model=self._model_tag(parsed),
                )

            await loop.run_in_executor(None, _journal)
        if parsed.stream:
            if resp is None:
                if error is not None:
                    return web.json_response(
                        public.error_body(503, error), status=503)
                resp = web.StreamResponse()
                resp.headers["Content-Type"] = "text/event-stream"
                resp.headers["Cache-Control"] = "no-cache"
                await resp.prepare(request)
            if error is not None:
                await resp.write(public.sse_event(
                    public.error_body(503, error)))
            final = (
                public.completion_chunk(rid, parsed.model, [],
                                        finish_reason=finish)
                if parsed.kind == "completion"
                else public.chat_chunk(rid, parsed.model, [],
                                       finish_reason=finish)
            )
            final["usage"] = public.usage_fields(
                len(parsed.prompt_ids), len(acc))
            await resp.write(public.sse_event(final))
            await resp.write(public.SSE_DONE)
            await resp.write_eof()
            return resp
        if error is not None and not acc:
            return web.json_response(
                public.error_body(503, error), status=503)
        body = (
            public.completion_body(rid, parsed.model, acc,
                                   len(parsed.prompt_ids), finish)
            if parsed.kind == "completion"
            else public.chat_body(rid, parsed.model, acc,
                                  len(parsed.prompt_ids), finish)
        )
        return web.json_response(body)

    # -- internal-surface auth ------------------------------------------

    def _internal_ok(self, request) -> bool:
        """True iff the caller presented the internal shared secret
        (X-Areal-Gateway-Token, or a Bearer token) — the gate on the
        trainer proxy and the operator surfaces, which share the
        tenant-facing listener."""
        tok = request.headers.get("X-Areal-Gateway-Token", "")
        if not tok:
            auth = request.headers.get("Authorization", "")
            tok = auth[7:] if auth.startswith("Bearer ") else ""
        return bool(tok) and hmac.compare_digest(
            tok, self.internal_token)

    # -- trainer proxy --------------------------------------------------

    async def _h_schedule_proxy(self, request):
        """Reserved-tenant pass-through for the training plane: tags
        the meta as the trainer tenant (never shed, never queued) and
        forwards to the manager with the caller's deadline intact.
        Internal-token gated: an unauthenticated caller would otherwise
        ride the never-shed trainer lane past every tenant quota."""
        from aiohttp import web

        if not self._internal_ok(request):
            self.counters["auth_failures_total"] += 1
            return web.json_response(
                public.error_body(
                    401, "trainer proxy requires the internal token"),
                status=401,
            )
        try:
            meta = await request.json()
        except Exception:
            meta = {}
        if not isinstance(meta, dict):
            meta = {}
        # Overwrite, never setdefault: the proxy's whole meaning is
        # "this IS trainer traffic" — an internal caller must not be
        # able to spoof some other tenant's attribution either.
        meta["tenant"] = TRAINER_TENANT
        self._trainer_sched += 1
        dl = rpc.ensure_deadline(
            rpc.Deadline.from_headers(request.headers),
            self.request_timeout,
        )
        sess = await self._sess()
        try:
            async with sess.post(
                f"{self.manager_addr}/schedule_request", json=meta,
                headers=dl.headers(),
            ) as r:
                body = await r.json()
                code = r.status
        except (aiohttp.ClientError, asyncio.TimeoutError):
            loop = asyncio.get_event_loop()
            await loop.run_in_executor(None, self._refresh_manager_addr)
            return web.json_response(
                {"error": "gserver manager unreachable",
                 "retry_after": 0.5},
                status=503,
            )
        return web.json_response(body, status=code)

    # -- operator surfaces ----------------------------------------------

    async def _h_usage(self, request):
        """Operator token -> every tenant's row; a tenant API key ->
        exactly that tenant's row; anyone else -> 401. Usage rows are
        per-tenant confidential (the Retry-After design already
        refuses to leak cross-tenant traffic — the report must not
        hand it out for free)."""
        from aiohttp import web

        operator = self._internal_ok(request)
        tenant: Optional[Tenant] = None
        if not operator:
            auth = request.headers.get("Authorization", "")
            key = auth[7:] if auth.startswith("Bearer ") else auth
            tenant = self._by_key.get(key)
            if tenant is None:
                self.counters["auth_failures_total"] += 1
                return web.json_response(
                    public.error_body(
                        401,
                        "usage requires the internal token or a "
                        "tenant API key",
                    ),
                    status=401,
                )
        snap = self.ledger.snapshot()
        if operator:
            trainer = snap.setdefault(TRAINER_TENANT, {
                "requests": 0, "sheds": 0, "prompt_tokens": 0,
                "completion_tokens": 0, "total_tokens": 0,
            })
            trainer["sched_requests"] = self._trainer_sched
        else:
            snap = {tenant.name: snap.get(tenant.name, {
                "requests": 0, "sheds": 0, "prompt_tokens": 0,
                "completion_tokens": 0, "total_tokens": 0,
            })}
        return web.json_response({
            "schema": GATEWAY_V1,
            "gateway": self.member,
            "fair_share": self.fair_share,
            "models": self.models,
            "usage_replayed": self.ledger.replayed,
            "usage_dup_dropped": self.ledger.dup_dropped,
            "usage_compactions": self.ledger.compactions,
            "tenants": snap,
        })

    async def _h_metrics(self, request):
        from aiohttp import web

        if not self._internal_ok(request):
            self.counters["auth_failures_total"] += 1
            return web.json_response(
                public.error_body(
                    401, "metrics requires the internal token"),
                status=401,
            )
        c = self.counters
        pt, ct, ttft, itl = self.ledger.totals()
        active = sum(t.active_streams for t in self.tenants.values())
        lines = [
            f"areal:gw_requests_total {c['requests_total']}",
            f"areal:gw_auth_failures_total {c['auth_failures_total']}",
            f"areal:gw_shed_total {c['shed_total']}",
            f"areal:gw_prompt_tokens_total {pt}",
            f"areal:gw_completion_tokens_total {ct}",
            f"areal:gw_active_streams {active}",
            f"areal:gw_queue_depth {self._queue_depth()}",
            f"areal:gw_fairshare_picks_total {c['fairshare_picks_total']}",
            f"areal:gw_ttft_hist {latency.encode_counts(ttft) or '-'}",
            f"areal:gw_itl_hist {latency.encode_counts(itl) or '-'}",
            f"areal:gw_upstream_failovers_total "
            f"{c['upstream_failovers_total']}",
            f"areal:gw_model_rejections_total "
            f"{c['model_rejections_total']}",
            f"areal:gw_usage_replayed_total {self.ledger.replayed}",
            f"areal:gw_usage_dup_dropped_total "
            f"{self.ledger.dup_dropped}",
            f"areal:gw_usage_compactions_total "
            f"{self.ledger.compactions}",
        ]
        return web.Response(text="\n".join(lines) + "\n")

    async def _h_health(self, request):
        from aiohttp import web

        return web.json_response({
            "status": "ok",
            "tenants": len(self.tenants),
            "manager_addr": self.manager_addr,
            "fair_share": self.fair_share,
        })

    # -- lifecycle -------------------------------------------------------

    def _run_http(self):
        from aiohttp import web

        self._http_loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._http_loop)
        self._queue_event = asyncio.Event()
        app = web.Application(client_max_size=64 << 20)
        app.router.add_post("/v1/completions", self._h_completions)
        app.router.add_post("/v1/chat/completions", self._h_chat)
        app.router.add_post("/schedule_request", self._h_schedule_proxy)
        app.router.add_get("/v1/usage", self._h_usage)
        app.router.add_get("/metrics", self._h_metrics)
        app.router.add_get("/health", self._h_health)
        runner = web.AppRunner(app)
        self._http_loop.run_until_complete(runner.setup())
        host = network.gethostip()
        port = self._port or network.find_free_port()
        ssl_ctx: Optional[ssl.SSLContext] = None
        if self._tls_cert and self._tls_key:
            ssl_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ssl_ctx.load_cert_chain(self._tls_cert, self._tls_key)
        site = web.TCPSite(runner, host, port, ssl_context=ssl_ctx)
        self._http_loop.run_until_complete(site.start())
        scheme = "https" if ssl_ctx is not None else "http"
        self.address = f"{scheme}://{host}:{port}"
        self._dispatch_task = self._http_loop.create_task(
            self._dispatch_loop())
        self._http_ready.set()
        self._http_loop.run_forever()

    def _supervise(self):
        ttl = self._heartbeat.ttl if self._heartbeat else 10.0
        while not self._stop.wait(max(0.05, ttl / 3)):
            if self._heartbeat is not None:
                # Per-tenant usage rides the heartbeat payload so the
                # manager's /status can surface tenant rows without a
                # new wire route.
                self._heartbeat.update_payload(
                    tenants=self.ledger.brief(),
                    sheds=self.counters["shed_total"],
                )

    def start(self, timeout: float = 30.0) -> str:
        if self.manager_addr is None:
            self._refresh_manager_addr()
        self._http_thread = threading.Thread(
            target=self._run_http, daemon=True, name="gw-http"
        )
        self._http_thread.start()
        if not self._http_ready.wait(timeout):
            raise TimeoutError("gateway HTTP front did not start")
        # Per-instance records (keyed by gateway_id, like every other
        # fleet member): concurrent gateways never overwrite — or on
        # stop() delete — each other's discovery state.
        name_resolve.add(
            names.gateway_url(
                self.experiment_name, self.trial_name, self.gateway_id),
            self.address,
            delete_on_exit=True,
            replace=True,
        )
        name_resolve.add(
            names.gateway_internal_token(
                self.experiment_name, self.trial_name, self.gateway_id),
            self.internal_token,
            delete_on_exit=True,
            replace=True,
        )
        self._heartbeat = Heartbeat(
            self.experiment_name,
            self.trial_name,
            self.member,
            payload={"url": self.address, "tenants": {}},
        )
        self._sup_thread = threading.Thread(
            target=self._supervise, daemon=True, name="gw-supervise"
        )
        self._sup_thread.start()
        logger.info(
            f"gateway {self.member} serving at {self.address} "
            f"({len(self.tenants)} tenants, fair_share="
            f"{self.fair_share}, manager={self.manager_addr})"
        )
        return self.address

    def stop(self):
        self._stop.set()
        if self._heartbeat is not None:
            self._heartbeat.stop()
        for key in (
            names.gateway_url(
                self.experiment_name, self.trial_name, self.gateway_id),
            names.gateway_internal_token(
                self.experiment_name, self.trial_name, self.gateway_id),
        ):
            try:
                name_resolve.delete(key)
            except Exception:
                pass
        if self._http_loop is not None:
            if self._session is not None and not self._session.closed:
                try:
                    asyncio.run_coroutine_threadsafe(
                        self._session.close(), self._http_loop
                    ).result(timeout=5)
                except Exception:
                    pass
            if self._dispatch_task is not None:
                task = self._dispatch_task

                async def _stop_dispatch():
                    task.cancel()
                    try:
                        await task
                    except asyncio.CancelledError:
                        pass

                try:
                    asyncio.run_coroutine_threadsafe(
                        _stop_dispatch(), self._http_loop
                    ).result(timeout=5)
                except Exception:
                    pass
            self._http_loop.call_soon_threadsafe(self._http_loop.stop)
        if self._http_thread is not None:
            self._http_thread.join(timeout=5)
        self.ledger.close()


# -- selftest ------------------------------------------------------------

class _StubUpstream:
    """In-process manager+server stand-in for ``--selftest``: answers
    /schedule_request with its own URL and /generate with two canned
    tokens then EOS, so the preflight exercises the full public path
    without a fleet."""

    def __init__(self):
        self._ready = threading.Event()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self.address: Optional[str] = None

    async def _h_sched(self, request):
        from aiohttp import web

        await request.json()
        return web.json_response({"url": self.address, "version": 0})

    async def _h_gen(self, request):
        from aiohttp import web

        await request.json()
        toks = list(b"ok")
        return web.json_response({
            "output_ids": toks,
            "output_logprobs": [0.0] * len(toks),
            "no_eos": False,
            "version_start": 0,
            "version_end": 0,
        })

    def _run(self):
        from aiohttp import web

        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        app = web.Application()
        app.router.add_post("/schedule_request", self._h_sched)
        app.router.add_post("/generate", self._h_gen)
        runner = web.AppRunner(app)
        self._loop.run_until_complete(runner.setup())
        host = network.gethostip()
        port = network.find_free_port()
        site = web.TCPSite(runner, host, port)
        self._loop.run_until_complete(site.start())
        self.address = f"http://{host}:{port}"
        self._ready.set()
        self._loop.run_forever()

    def start(self, timeout: float = 10.0):
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="gw-selftest-stub")
        self._thread.start()
        if not self._ready.wait(timeout):
            raise TimeoutError("selftest stub did not start")

    def stop(self):
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=5)


def _selftest_tls(stub: _StubUpstream, policy) -> None:
    """Self-signed-cert arm: mint a throwaway cert pair (openssl
    binary), serve a second gateway over https with a two-model
    fleet spec, and drive one completion + the 404/403 model
    refusals through the TLS listener. Raises on any failure; a box
    without the openssl binary skips the arm (the production stance
    is mTLS at the LB anyway — docs/serving.md)."""
    import shutil
    import subprocess
    import urllib.error
    import urllib.request

    if not shutil.which("openssl"):
        print("gateway selftest: openssl missing, TLS arm skipped")
        return
    tls_dir = tempfile.mkdtemp(prefix="gw_selftest_tls_")
    cert = os.path.join(tls_dir, "cert.pem")
    keyf = os.path.join(tls_dir, "key.pem")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", keyf, "-out", cert, "-days", "1",
         "-subj", "/CN=localhost"],
        check=True, capture_output=True,
    )
    wal2 = os.path.join(
        tempfile.gettempdir(), f"gw_selftest_tls_{os.getpid()}.jsonl")
    try:
        os.remove(wal2)
    except OSError:
        pass
    os.environ["AREAL_GW_TLS_CERT"] = cert
    os.environ["AREAL_GW_TLS_KEY"] = keyf
    svc = None
    try:
        svc = GatewayService(
            "gw_selftest_tls", "local",
            manager_addr=stub.address,
            tenant_spec="selftest:sk-selftest:1:100000:200000:4:alpha",
            usage_wal_path=wal2,
            model_spec="alpha,beta",
        )
        url = svc.start()
        assert url.startswith("https://"), url
        unverified = ssl._create_unverified_context()
        hdrs = {"Authorization": "Bearer sk-selftest",
                "Content-Type": "application/json"}

        def _post(model):
            data = json.dumps({"prompt": "hi", "max_tokens": 2,
                               "stream": False,
                               "model": model}).encode()
            req = urllib.request.Request(
                f"{url}/v1/completions", data=data, headers=hdrs)
            probe_dl = rpc.Deadline.after(policy.attempt_timeout_s)
            with urllib.request.urlopen(
                req, timeout=policy.attempt_timeout(probe_dl),
                context=unverified,
            ) as r:
                return json.loads(r.read().decode())

        body = _post("alpha")
        assert body["model"] == "alpha", body
        for model, want in (("nope", 404), ("beta", 403)):
            try:
                _post(model)
                raise AssertionError(
                    f"model {model!r} was not refused")
            except urllib.error.HTTPError as e:
                assert e.code == want, (model, e.code)
        snap = svc.ledger.snapshot()["selftest"]
        assert snap["models"]["alpha"]["requests"] == 1, snap
        print(f"gateway selftest TLS arm ok: {url}")
    finally:
        os.environ.pop("AREAL_GW_TLS_CERT", None)
        os.environ.pop("AREAL_GW_TLS_KEY", None)
        if svc is not None:
            svc.stop()
        shutil.rmtree(tls_dir, ignore_errors=True)
        try:
            os.remove(wal2)
        except OSError:
            pass


def _selftest() -> int:
    import urllib.error
    import urllib.request

    stub = _StubUpstream()
    stub.start()
    wal_path = os.path.join(
        tempfile.gettempdir(), f"gw_selftest_{os.getpid()}.jsonl")
    try:
        os.remove(wal_path)
    except OSError:
        pass
    svc = GatewayService(
        "gw_selftest", "local",
        manager_addr=stub.address,
        tenant_spec="selftest:sk-selftest:1:100000:200000:4",
        usage_wal_path=wal_path,
    )
    url = svc.start()
    policy = rpc.default_policy()
    try:
        data = json.dumps(
            {"prompt": "hi", "max_tokens": 4, "stream": True}).encode()
        req = urllib.request.Request(
            f"{url}/v1/completions", data=data,
            headers={"Authorization": "Bearer sk-selftest",
                     "Content-Type": "application/json"},
        )
        probe_dl = rpc.Deadline.after(policy.attempt_timeout_s)
        with urllib.request.urlopen(
            req, timeout=policy.attempt_timeout(probe_dl)
        ) as r:
            text = r.read().decode()
        assert "[DONE]" in text, text
        assert '"finish_reason":"stop"' in text, text
        data = json.dumps({
            "messages": [{"role": "user", "content": "hi"}],
            "stream": False,
        }).encode()
        req = urllib.request.Request(
            f"{url}/v1/chat/completions", data=data,
            headers={"Authorization": "Bearer sk-selftest",
                     "Content-Type": "application/json"},
        )
        probe_dl = rpc.Deadline.after(policy.attempt_timeout_s)
        with urllib.request.urlopen(
            req, timeout=policy.attempt_timeout(probe_dl)
        ) as r:
            chat = json.loads(r.read().decode())
        assert chat["usage"]["completion_tokens"] >= 1, chat
        # Operator surfaces and the trainer proxy are internal-token
        # gated: no token -> 401, the minted token -> full view.
        op_hdr = {INTERNAL_TOKEN_HEADER: svc.internal_token}
        try:
            probe_dl = rpc.Deadline.after(policy.attempt_timeout_s)
            urllib.request.urlopen(
                urllib.request.Request(f"{url}/v1/usage"),
                timeout=policy.attempt_timeout(probe_dl),
            )
            raise AssertionError("tokenless /v1/usage was not refused")
        except urllib.error.HTTPError as e:
            assert e.code == 401, e.code
        probe_dl = rpc.Deadline.after(policy.attempt_timeout_s)
        with urllib.request.urlopen(
            urllib.request.Request(f"{url}/v1/usage", headers=op_hdr),
            timeout=policy.attempt_timeout(probe_dl),
        ) as r:
            usage = json.loads(r.read().decode())
        row = usage["tenants"]["selftest"]
        assert row["requests"] == 2, usage
        assert row["completion_tokens"] >= 2, usage
        probe_dl = rpc.Deadline.after(policy.attempt_timeout_s)
        with urllib.request.urlopen(
            urllib.request.Request(f"{url}/metrics", headers=op_hdr),
            timeout=policy.attempt_timeout(probe_dl),
        ) as r:
            mtext = r.read().decode()
        assert "areal:gw_requests_total 2" in mtext, mtext
        _selftest_tls(stub, policy)
        print(f"gateway selftest ok: {url}")
        return 0
    except Exception as e:
        print(f"gateway selftest FAILED: {e}", file=sys.stderr)
        return 1
    finally:
        svc.stop()
        stub.stop()
        try:
            os.remove(wal_path)
        except OSError:
            pass


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        description="multi-tenant inference gateway")
    p.add_argument("--experiment", default="gateway")
    p.add_argument("--trial", default="local")
    p.add_argument("--index", type=int, default=0)
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--manager-addr", default=None)
    p.add_argument("--tenants", default=None,
                   help="overrides AREAL_GW_TENANTS")
    p.add_argument("--models", default=None,
                   help="comma list of served model ids (first is the "
                   "default); overrides AREAL_GW_MODELS")
    p.add_argument("--usage-wal", default=None)
    p.add_argument("--name-resolve-root", default=None)
    p.add_argument(
        "--selftest",
        action="store_true",
        help="serve against an in-process stub fleet, run one "
        "completion + one chat completion through the full tenant "
        "path, check the ledger; exit 0 iff healthy (chip_runbook "
        "preflight)",
    )
    args = p.parse_args(argv)
    if args.name_resolve_root:
        name_resolve.reconfigure("nfs", record_root=args.name_resolve_root)
    else:
        name_resolve.reconfigure("memory")
    if args.selftest:
        return _selftest()
    svc = GatewayService(
        args.experiment, args.trial, gateway_id=args.index,
        port=args.port, manager_addr=args.manager_addr,
        tenant_spec=args.tenants, usage_wal_path=args.usage_wal,
        model_spec=args.models,
    )
    url = svc.start()
    print(url, flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        svc.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""areal-lint unit fixtures + the tier-1 gate.

One positive and one negative snippet per checker (the seeded
violations the acceptance criteria require), allowlist semantics
(honored, justification mandatory, stale entries reported), and a gate
run over the real tree: zero unallowlisted findings, no jax import,
env-docs drift-free. Fixtures are AST-parsed, never imported, so they
need no runnable dependencies."""

import os
import subprocess
import sys
import textwrap

import pytest

from areal_tpu.lint.common import LintConfigError, parse_allowlist
from areal_tpu.lint.env_knobs import EnvKnobConfig
from areal_tpu.lint.runner import LintConfig, run_lint

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)
)))


def _lint(tmp_path, source, *, name="mod.py", checkers=None, env_cfg=None,
          allowlist=None, check_dead=False, wire_rel="wire_schemas.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    cfg = LintConfig(
        root=str(tmp_path),
        allowlist_path=str(allowlist) if allowlist else None,
        env_cfg=env_cfg,
        check_dead_knobs=check_dead,
        wire_constants_rel=wire_rel,
        checkers=set(checkers) if checkers else {
            "loop-only", "blocking-async", "env-knob", "wire-schema",
        },
    )
    return run_lint([str(p)], cfg)


def _keys(findings):
    return [(f.path, f.line, f.checker) for f in findings]


# ----------------------------------------------------------------------
# blocking-async
# ----------------------------------------------------------------------


def test_blocking_async_positive(tmp_path):
    findings = _lint(tmp_path, """
        import time

        async def handler(request):
            time.sleep(1)
    """, checkers=["blocking-async"])
    assert len(findings) == 1
    assert findings[0].checker == "blocking-async"
    assert "time.sleep" in findings[0].message


def test_blocking_async_executor_wrap_is_clean(tmp_path):
    findings = _lint(tmp_path, """
        import asyncio
        import time

        async def handler(request):
            def _work():
                time.sleep(1)
                return open("/tmp/x").read()
            return await asyncio.get_running_loop().run_in_executor(
                None, _work
            )
    """, checkers=["blocking-async"])
    assert findings == []


def test_blocking_async_direct_nested_call_flagged(tmp_path):
    findings = _lint(tmp_path, """
        import time

        async def handler(request):
            def _work():
                time.sleep(1)
            _work()
    """, checkers=["blocking-async"])
    assert len(findings) == 1
    assert "_work" in findings[0].message


def test_blocking_async_transitive_method_flagged(tmp_path):
    findings = _lint(tmp_path, """
        import json

        class S:
            def _load(self):
                with open("/tmp/s.json") as f:
                    return json.load(f)

            def _hop(self):
                return self._load()

            async def handler(self, request):
                return self._hop()
    """, checkers=["blocking-async"])
    assert len(findings) == 1
    assert "self._hop()" in findings[0].message


# ----------------------------------------------------------------------
# env-knob
# ----------------------------------------------------------------------

_ENV_CFG = EnvKnobConfig(
    declared={"AREAL_DECLARED", "AREAL_DEAD"},
    accessor_names=("get_raw", "get_str", "get_int", "get_float",
                    "get_bool", "is_set"),
    registry_rel="env_registry.py",
    registry_module="areal_tpu.base.env_registry",
)


def test_env_knob_undeclared_read_flagged(tmp_path):
    findings = _lint(tmp_path, """
        import os
        x = os.environ.get("AREAL_NOT_DECLARED")
    """, checkers=["env-knob"], env_cfg=_ENV_CFG)
    assert len(findings) == 1
    assert "undeclared env knob AREAL_NOT_DECLARED" in findings[0].message


def test_env_knob_raw_read_of_declared_flagged(tmp_path):
    findings = _lint(tmp_path, """
        import os
        x = os.getenv("AREAL_DECLARED", "1")
    """, checkers=["env-knob"], env_cfg=_ENV_CFG)
    assert len(findings) == 1
    assert "raw os.environ read" in findings[0].message


def test_env_knob_accessor_read_is_clean(tmp_path):
    findings = _lint(tmp_path, """
        from areal_tpu.base import env_registry
        x = env_registry.get_str("AREAL_DECLARED")
    """, checkers=["env-knob"], env_cfg=_ENV_CFG)
    assert findings == []


def test_env_knob_name_resolved_through_constant(tmp_path):
    findings = _lint(tmp_path, """
        import os
        _ENV = "AREAL_NOT_DECLARED"
        x = os.environ.get(_ENV)
    """, checkers=["env-knob"], env_cfg=_ENV_CFG)
    assert len(findings) == 1
    assert "AREAL_NOT_DECLARED" in findings[0].message


def test_env_knob_dynamic_name_flagged(tmp_path):
    findings = _lint(tmp_path, """
        import os
        k = "BQ"
        x = os.environ.get(f"AREAL_SPLASH_{k}")
    """, checkers=["env-knob"], env_cfg=_ENV_CFG)
    assert len(findings) == 1
    assert "dynamically-built" in findings[0].message


def test_env_knob_dead_entry_flagged(tmp_path):
    (tmp_path / "env_registry.py").write_text(
        'Knob = dict\n_k = dict\n'
        'REGISTRY = {}\n'
    )
    mod = tmp_path / "user.py"
    mod.write_text(
        "from areal_tpu.base import env_registry\n"
        'x = env_registry.get_str("AREAL_DECLARED")\n'
    )
    cfg = LintConfig(
        root=str(tmp_path), env_cfg=_ENV_CFG, check_dead_knobs=True,
        checkers={"env-knob"},
    )
    findings = run_lint([str(tmp_path)], cfg)
    assert len(findings) == 1
    assert "dead registry entry AREAL_DEAD" in findings[0].message


# ----------------------------------------------------------------------
# wire-schema
# ----------------------------------------------------------------------


def test_wire_schema_literal_flagged(tmp_path):
    findings = _lint(tmp_path, """
        SCHEMA = "areal-my-thing/v1"
    """, checkers=["wire-schema"])
    assert len(findings) == 1
    assert "areal-my-thing/v1" in findings[0].message


def test_wire_schema_constants_module_and_prose_exempt(tmp_path):
    # the constants module itself
    assert _lint(tmp_path, """
        KV = "areal-kv-handoff/v1"
    """, name="wire_schemas.py", checkers=["wire-schema"]) == []
    # prose mentioning a schema inside a longer string
    assert _lint(tmp_path, """
        DOC = "the payload follows areal-kv-handoff/v1 framing"
    """, checkers=["wire-schema"]) == []


# ----------------------------------------------------------------------
# loop-only
# ----------------------------------------------------------------------

_LOOP_FIXTURE = """
    AREAL_LINT_LOOP_ONLY = {{
        "Engine": {{
            "roots": ["_loop"],
            "door": "_run_on_loop",
            "attrs": ["_backlog"],
            "instance_hints": ["engine"],
        }},
    }}

    class Engine:
        def __init__(self):
            self._backlog = []

        def _run_on_loop(self, fn):
            return fn()

        def _loop(self):
            self._serve()

        def _serve(self):
            self._backlog.append(1)

        def off_thread(self):
            {off_thread_body}
"""


def test_loop_only_off_thread_access_flagged(tmp_path):
    findings = _lint(
        tmp_path,
        _LOOP_FIXTURE.format(off_thread_body="return len(self._backlog)"),
        checkers=["loop-only"],
    )
    assert len(findings) == 1
    assert "_backlog" in findings[0].message
    assert "off_thread" in findings[0].message


def test_loop_only_door_closure_is_clean(tmp_path):
    findings = _lint(
        tmp_path,
        _LOOP_FIXTURE.format(
            off_thread_body=(
                "return self._run_on_loop(lambda: len(self._backlog))"
            )
        ),
        checkers=["loop-only"],
    )
    assert findings == []


def test_loop_only_instance_hint_cross_module(tmp_path):
    (tmp_path / "eng.py").write_text(textwrap.dedent(
        _LOOP_FIXTURE.format(off_thread_body="pass")
    ))
    (tmp_path / "server.py").write_text(textwrap.dedent("""
        class Server:
            async def handler(self, request):
                return len(self.engine._backlog)
    """))
    cfg = LintConfig(root=str(tmp_path), checkers={"loop-only"})
    findings = run_lint([str(tmp_path)], cfg)
    assert _keys(findings) == [("server.py", 4, "loop-only")]


# ----------------------------------------------------------------------
# allowlist
# ----------------------------------------------------------------------


def test_allowlist_honored_and_stale_reported(tmp_path):
    allow = tmp_path / "allow.txt"
    allow.write_text(
        "# comment\n"
        "mod.py:5 blocking-async -- event loop is quiesced here\n"
        "mod.py:99 blocking-async -- stale entry (line drifted away)\n"
    )
    findings = _lint(tmp_path, """
        import time

        async def handler(request):
            time.sleep(1)
    """, checkers=["blocking-async"], allowlist=allow)
    # the real finding is waived; the in-scope stale entry surfaces
    assert _keys(findings) == [("allow.txt", 3, "allowlist")]


def test_allowlist_out_of_scope_entries_not_stale(tmp_path):
    """A subset run (one checker / one file) never generates waived
    findings for other checkers/files — those entries must not be
    reported stale, or every `--checker X` run fails spuriously."""
    allow = tmp_path / "allow.txt"
    allow.write_text(
        "mod.py:5 env-knob -- different checker, not active this run\n"
        "other.py:7 blocking-async -- file not scanned this run\n"
    )
    findings = _lint(tmp_path, """
        import time

        async def handler(request):
            pass
    """, checkers=["blocking-async"], allowlist=allow)
    assert findings == []


def test_allowlist_requires_justification(tmp_path):
    allow = tmp_path / "allow.txt"
    allow.write_text("mod.py:5 blocking-async\n")
    with pytest.raises(LintConfigError):
        parse_allowlist(str(allow))
    allow.write_text("mod.py:5 blocking-async -- \n")
    with pytest.raises(LintConfigError):
        parse_allowlist(str(allow))


# ----------------------------------------------------------------------
# tier-1 gate
# ----------------------------------------------------------------------


def test_gate_tree_is_clean_no_jax_and_docs_fresh():
    """THE gate: linting areal_tpu/ with all eight checkers finds
    nothing unallowlisted, never imports jax (AST-only — this is what
    keeps it <10s on the 2-core host), and every generated doc
    (env_vars, metrics, fault_points) matches its registry."""
    code = (
        "import sys\n"
        "from areal_tpu.lint.cli import main\n"
        "rc = main(['areal_tpu',\n"
        "           '--check-env-docs', 'docs/env_vars.md',\n"
        "           '--check-metrics-docs', 'docs/metrics.md',\n"
        "           '--check-fault-docs', 'docs/fault_points.md'])\n"
        "assert 'jax' not in sys.modules, 'lint gate imported jax'\n"
        "sys.exit(rc)\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=REPO_ROOT,
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, (
        f"areal-lint gate failed:\n{proc.stdout}\n{proc.stderr}"
    )


def test_gate_cross_process_contracts_cover_tests_and_scripts():
    """Tests and scripts are the CLIENT side of the wire/metrics/chaos
    contracts (AREAL_FAULTS specs, /metrics passthroughs, bench route
    calls), so the three cross-process checkers sweep them too. The
    older single-process checkers (env-knob, loop-only, ...) stay
    scoped to areal_tpu/ — test-local knobs are legitimate."""
    code = (
        "import sys\n"
        "from areal_tpu.lint.cli import main\n"
        "rc = main(['tests', 'scripts',\n"
        "           '--checker', 'wire-contract',\n"
        "           '--checker', 'metrics-registry',\n"
        "           '--checker', 'chaos-registry'])\n"
        "assert 'jax' not in sys.modules, 'lint gate imported jax'\n"
        "sys.exit(rc)\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=REPO_ROOT,
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, (
        f"areal-lint cross-process gate failed:\n"
        f"{proc.stdout}\n{proc.stderr}"
    )


def test_gate_cli_seeded_violation_fires(tmp_path):
    """End-to-end CLI run over a seeded violation: nonzero exit + a
    rendered finding line."""
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import time\n\n\nasync def h(r):\n    time.sleep(1)\n"
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "scripts", "areal_lint.py"),
         str(bad)],
        capture_output=True, text=True, timeout=60, cwd=REPO_ROOT,
    )
    assert proc.returncode == 1
    assert "blocking-async" in proc.stdout

"""Trainer -> generation-server weight transfer with a same-host fast path.

Counterpart of the reference's param-realloc transfer stack
(realhf/system/model_worker.py:1046-1148 — disk-mediated by default, with
NCCL/GDRDMA fast paths keeping it under the <3 s bar of
blog/AReaL_v0_2.md:52-54). The TPU single-host equivalent of the CUDA-IPC
path is raw parameter bytes in tmpfs (/dev/shm) read back with mmap: no
pickle serialize/deserialize copies, no disk IO, and `jax.device_put`
streams straight from the mapped pages. The pickle-on-NFS dump
(engine/checkpoint.py) remains the cross-host fallback.

Format (per dump directory):
- ``params-v{N}.bin``  — every leaf's contiguous bytes, concatenated.
- ``params.json``      — manifest: schema version, dump version N, bin
  filename, and per-leaf (path, dtype, shape, offset). Written via
  tmp+rename AFTER the bin, so a reader that sees a manifest always sees
  its complete bin. Older bins are garbage-collected down to the last 2;
  a reader racing the GC gets FileNotFoundError and falls back.

The tree is assumed to be nested dicts of arrays (what
models/transformer.init_params builds); list/tuple nodes are rejected at
dump time rather than silently mis-rebuilt.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from areal_tpu.base import logging
from areal_tpu.base.chunking import DEFAULT_CHUNK_BYTES, StreamChunker

logger = logging.getLogger("weight_transfer")

_MANIFEST = "params.json"
_SCHEMA = 1

LAYOUT_SCHEMA = "areal-weight-layout/v1"

# Quantized-wire convention (mirrors ops/wquant.py): symmetric int8 with
# per-output-channel scales reduced over axis -2, w ~= q * s. Slicing any
# dimension commutes with the dequant (s broadcasts along -2 only), so a
# shard of the quantized bin dequantizes to exactly the shard of the
# dequantized full bin — the property the weight plane's dequant-parity
# check asserts.
_WIRE_Q = 127.0
_WIRE_QAXIS = -2

# Leaf NAMES the int8 wire quantizes: the matmul weights + embedding/LM
# head — the bulk of the payload. Kept in sync with ops/wquant._QUANT_KEYS
# (weight_transfer stays jax-free, so no import); norms, biases, router
# tables, and integer leaves ship raw — the small +epsilon of a dump.
WIRE_QUANT_KEYS = frozenset({
    "wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "w_in", "w_out",
    "weight", "w",
})


class WeightVersionMismatch(RuntimeError):
    """load_for_serving found weights, but not the requested version.

    Serving them anyway would pin a stale (or unverifiable, version -1
    pickle/HF) dump under the new version label — the exact accounting
    hole the staleness gate can't see. Callers fail the update instead;
    the manager's eviction/readmission path re-syncs the server."""


def shm_transfer_dir(experiment_name: str, trial_name: str, role: str) -> Optional[str]:
    """tmpfs dump directory for the same-host fast path, or None when
    /dev/shm is unavailable (then only the disk path is used)."""
    base = "/dev/shm"
    if not os.path.isdir(base) or not os.access(base, os.W_OK):
        return None
    return os.path.join(base, "areal_tpu", experiment_name, trial_name, role)


def _flatten(params: Any, prefix: Tuple[str, ...] = ()) -> list:
    out = []
    if isinstance(params, dict):
        for k in sorted(params.keys()):
            out.extend(_flatten(params[k], prefix + (str(k),)))
        return out
    if isinstance(params, (list, tuple)):
        raise TypeError(
            f"weight_transfer supports dict-of-array trees only; found "
            f"{type(params).__name__} at {'/'.join(prefix)}"
        )
    return [("/".join(prefix), params)]


def chunk_sidecar_name(bin_name: str) -> str:
    """Chunk-index sidecar for a bin (``params-v{N}.chunks.json``)."""
    return bin_name[: -len(".bin")] + ".chunks.json"


def layout_sidecar_name(bin_name: str) -> str:
    """Per-leaf layout sidecar for a bin (``params-v{N}.layout.json``):
    path -> dtype/shape -> byte extent. Makes each bin self-describing
    (params.json only describes the NEWEST dump, but GC keeps two bins)
    and is what the weight plane's shard manifests slice against."""
    return bin_name[: -len(".bin")] + ".layout.json"


def wire_bin_name(version: int, wire_dtype: str) -> str:
    """The quantized-wire companion bin (``params-v{N}.int8.bin``)."""
    return f"params-v{version}.{wire_dtype}.bin"


def _wire_quantizable(path: str, arr: np.ndarray) -> bool:
    """Leaves the int8 wire quantizes: float matrices (ndim >= 2) whose
    leaf name marks a matmul weight / embedding (WIRE_QUANT_KEYS).
    Everything else ships raw — the scale convention needs an input dim
    and norm/bias precision is not worth trading for their few bytes."""
    return (
        arr.ndim >= 2
        and path.split("/")[-1] in WIRE_QUANT_KEYS
        and (
            np.issubdtype(arr.dtype, np.floating)
            or arr.dtype.name == "bfloat16"
        )
    )


def quantize_wire_leaf(arr: np.ndarray):
    """(int8 data, float32 scales) for one leaf under the wire
    convention (see _WIRE_Q/_WIRE_QAXIS). Host-side numpy mirror of
    ops/wquant.quantize_weight, bit-equal in convention so W8A16
    serving could adopt wire-quantized leaves without requantizing."""
    w32 = np.asarray(arr, dtype=np.float32)
    s = np.maximum(np.max(np.abs(w32), axis=_WIRE_QAXIS), 1e-8) / _WIRE_Q
    q = np.clip(
        np.rint(w32 / np.expand_dims(s, _WIRE_QAXIS)), -_WIRE_Q, _WIRE_Q
    ).astype(np.int8)
    return q, s.astype(np.float32)


def dequantize_wire_leaf(q: np.ndarray, s: np.ndarray, dtype) -> np.ndarray:
    """Inverse of quantize_wire_leaf, cast back to the logical dtype."""
    return (
        q.astype(np.float32) * np.expand_dims(s, _WIRE_QAXIS)
    ).astype(dtype)


def _write_json_atomic(dump_dir: str, name: str, payload: Dict) -> None:
    tmp = os.path.join(dump_dir, name + f".tmp.{os.getpid()}")
    with open(tmp, "w") as f:
        json.dump(payload, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(dump_dir, name))


def _dump_wire_bin(
    dump_dir: str, version: int, wire_dtype: str,
    leaves, chunk_bytes: int,
) -> Dict[str, Any]:
    """Write the quantized-wire companion bin + its chunk/layout
    sidecars; returns the layout dict. Per leaf the int8 data slab is
    immediately followed by its float32 scale slab, so a shard manifest
    slices them as adjacent segments of one stream."""
    if wire_dtype != "int8":
        raise ValueError(f"unsupported weight_wire_dtype {wire_dtype!r}")
    bin_name = wire_bin_name(version, wire_dtype)
    layout: Dict[str, Any] = {
        "schema": LAYOUT_SCHEMA, "version": int(version), "bin": bin_name,
        "wire": wire_dtype, "leaves": [],
    }
    offset = 0
    chunker = StreamChunker(chunk_bytes)
    tmp_bin = os.path.join(dump_dir, bin_name + f".tmp.{os.getpid()}")
    with open(tmp_bin, "wb") as f:

        def put(data: bytes):
            nonlocal offset
            f.write(data)
            chunker.update(data)
            offset += len(data)

        for path, leaf in leaves:
            arr = np.ascontiguousarray(np.asarray(leaf))
            entry: Dict[str, Any] = {
                "path": path, "dtype": arr.dtype.name,
                "shape": list(arr.shape), "offset": offset,
            }
            if _wire_quantizable(path, arr):
                q, s = quantize_wire_leaf(arr)
                entry.update(
                    wire="int8", nbytes=q.nbytes,
                    scale_offset=offset + q.nbytes, scale_nbytes=s.nbytes,
                    scale_shape=list(s.shape), scale_dtype="float32",
                )
                put(q.tobytes())
                put(s.tobytes())
            else:
                entry.update(wire="raw", nbytes=arr.nbytes)
                put(arr.tobytes())
            layout["leaves"].append(entry)
        f.flush()
        os.fsync(f.fileno())
    layout["total_bytes"] = offset
    os.replace(tmp_bin, os.path.join(dump_dir, bin_name))
    _write_json_atomic(dump_dir, chunk_sidecar_name(bin_name), chunker.finish())
    _write_json_atomic(dump_dir, layout_sidecar_name(bin_name), layout)
    return layout


def dump_raw_params(
    params: Any, dump_dir: str, version: int,
    chunk_bytes: int = DEFAULT_CHUNK_BYTES,
    wire_dtype: Optional[str] = None,
) -> float:
    """Write the raw dump; returns seconds spent. Safe against concurrent
    readers (see module docstring); single writer assumed (the dp-rank-0
    dump rule, system/model_worker._param_realloc).

    Also publishes per-bin sidecars the weight-distribution plane serves
    from without re-reading the multi-GB bin:

    - ``params-v{N}.chunks.json`` — content hashes of the bin's
      fixed-size chunks, computed while the bytes stream through this
      loop anyway (``chunk_bytes`` should match the plane's
      ``weight_chunk_bytes`` knob; a mismatched sidecar is ignored).
    - ``params-v{N}.layout.json`` — per-leaf path/dtype/shape/byte
      extent, making the bin self-describing (params.json only describes
      the newest dump while GC keeps two) and sliceable into per-shard
      manifests.
    - with ``wire_dtype="int8"``: ``params-v{N}.int8.bin`` + its own
      sidecars — each float matrix leaf quantized to int8 data +
      float32 per-output-channel scales (ops/wquant.py convention),
      roughly halving bytes on the wire per version again; servers
      dequantize at assembly.
    """
    t0 = time.monotonic()
    os.makedirs(dump_dir, exist_ok=True)
    leaves = _flatten(params)
    bin_name = f"params-v{version}.bin"
    manifest: Dict[str, Any] = {
        "schema": _SCHEMA, "version": int(version), "bin": bin_name,
        "leaves": [],
    }
    offset = 0
    chunker = StreamChunker(chunk_bytes)
    tmp_bin = os.path.join(dump_dir, bin_name + f".tmp.{os.getpid()}")
    with open(tmp_bin, "wb") as f:
        for path, leaf in leaves:
            arr = np.ascontiguousarray(np.asarray(leaf))
            data = arr.tobytes()
            f.write(data)
            chunker.update(data)
            # dtype.name (not .str): ml_dtypes types like bfloat16 have
            # .str '<V2' which round-trips to a raw void type.
            manifest["leaves"].append(
                {"path": path, "dtype": arr.dtype.name,
                 "shape": list(arr.shape), "offset": offset,
                 "nbytes": arr.nbytes}
            )
            offset += arr.nbytes
        # fsync BEFORE the rename pair below: rename ordering alone is
        # only crash-safe within one file. Without it a host crash can
        # persist the (later-written) manifest but not the bin's data
        # blocks — a manifest pointing at unsynced bytes that would pass
        # the size check and serve garbage weights.
        f.flush()
        os.fsync(f.fileno())
    manifest["total_bytes"] = offset
    os.replace(tmp_bin, os.path.join(dump_dir, bin_name))
    _write_json_atomic(dump_dir, chunk_sidecar_name(bin_name), chunker.finish())
    _write_json_atomic(
        dump_dir, layout_sidecar_name(bin_name),
        {"schema": LAYOUT_SCHEMA, "version": int(version), "bin": bin_name,
         "wire": "raw", "total_bytes": offset,
         "leaves": [dict(e, wire="raw") for e in manifest["leaves"]]},
    )
    if wire_dtype not in (None, "model", "raw"):
        # Quantize during the dump pass (before the manifest lands), so
        # a reader that sees params.json advertise the wire can rely on
        # the wire bin existing for that version.
        wire_layout = _dump_wire_bin(
            dump_dir, version, wire_dtype, leaves, chunk_bytes
        )
        manifest["wire_dtypes"] = [wire_dtype]
        manifest["wire_total_bytes"] = {
            wire_dtype: wire_layout["total_bytes"]
        }
    _write_json_atomic(dump_dir, _MANIFEST, manifest)
    # GC old versions (bins + every sidecar/wire companion; keep the
    # newest 2 so an in-flight reader can finish).
    versions = set()
    for b in os.listdir(dump_dir):
        if b.startswith("params-v") and b.endswith(".bin"):
            v = b[len("params-v"):-len(".bin")].split(".", 1)[0]
            if v.isdigit():
                versions.add(int(v))
    for v in sorted(versions)[:-2]:
        victims = []
        for b in (f"params-v{v}.bin", wire_bin_name(v, "int8")):
            victims += [b, chunk_sidecar_name(b), layout_sidecar_name(b)]
        for victim in victims:
            try:
                os.unlink(os.path.join(dump_dir, victim))
            except OSError:
                pass
    return time.monotonic() - t0


def unflatten_leaves(leaves: Dict[str, np.ndarray]) -> Any:
    """path->array mapping back into the nested-dict pytree (shared with
    the weight plane's host-buffer assembly, engine/weight_client.py)."""
    root: Dict[str, Any] = {}
    for path, arr in leaves.items():
        node = root
        parts = path.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return root


def read_layout_sidecar(
    dump_dir: str, bin_name: str
) -> Optional[Dict[str, Any]]:
    """The bin's layout sidecar, or None when absent/malformed (callers
    synthesize a raw layout from params.json for pre-sidecar dumps)."""
    try:
        with open(os.path.join(dump_dir, layout_sidecar_name(bin_name))) as f:
            layout = json.load(f)
    except (OSError, ValueError, json.JSONDecodeError):
        return None
    if layout.get("schema") != LAYOUT_SCHEMA:
        return None
    return layout


def _read_manifest(dump_dir: str) -> Optional[Dict[str, Any]]:
    try:
        with open(os.path.join(dump_dir, _MANIFEST)) as f:
            manifest = json.load(f)
    except (OSError, ValueError, json.JSONDecodeError):
        return None
    if manifest.get("schema") != _SCHEMA:
        return None
    return manifest


def load_raw_params(dump_dir: str) -> Optional[Tuple[Any, int]]:
    """mmap the latest raw dump: (params pytree of memory-mapped arrays,
    dump version), or None if absent/torn (caller falls back).

    A reader can race the dump GC: the manifest it read names a bin the
    writer just unlinked (GC keeps only the newest 2). That race means a
    NEWER dump exists — re-read the manifest once and retry against it
    rather than silently falling through to a stale pickle."""
    import ml_dtypes  # noqa: F401  registers bfloat16 et al. by name

    for _attempt in range(2):
        manifest = _read_manifest(dump_dir)
        if manifest is None:
            return None
        try:
            mm = np.memmap(
                os.path.join(dump_dir, manifest["bin"]), mode="r",
                dtype=np.uint8,
            )
        except FileNotFoundError:
            continue  # GC race: refreshed manifest names the new bin
        except (OSError, ValueError, KeyError):
            return None  # malformed manifest: caller falls back
        try:
            if mm.size != manifest["total_bytes"]:
                return None  # torn write
            leaves = {}
            for e in manifest["leaves"]:
                dt = np.dtype(e["dtype"])
                n = int(np.prod(e["shape"])) * dt.itemsize
                leaves[e["path"]] = (
                    mm[e["offset"]: e["offset"] + n].view(dt).reshape(e["shape"])
                )
            return unflatten_leaves(leaves), int(manifest["version"])
        except (ValueError, KeyError):
            return None
    return None


def _load_once(
    model_path: str,
    shm_dir: Optional[str],
    t0: float,
    want_version: Optional[int] = None,
    raw_seen: Optional[Dict[str, int]] = None,
):
    """One pass down the fallback chain. With ``want_version`` pinned, a
    raw dump holding the WRONG version falls through to the next source
    instead of shadowing it — e.g. a tmpfs dump lagging one version
    behind the NFS dump (writer crashed between the two dumps) must not
    hide the matching disk copy. Mismatched raw versions are recorded in
    ``raw_seen`` for the caller's error message."""
    if shm_dir is not None:
        got = load_raw_params(shm_dir)
        if got is not None:
            params, v = got
            if want_version is None or v == want_version:
                return params, {"source": "shm_raw", "version": v,
                                "load_s": time.monotonic() - t0}
            if raw_seen is not None:
                raw_seen["shm_raw"] = v
    got = load_raw_params(model_path)
    if got is not None:
        params, v = got
        if want_version is not None and v != want_version and raw_seen is not None:
            raw_seen["disk_raw"] = v
        # A mismatched disk raw still ends the chain: pickle/HF below
        # are version -1 (strictly less informative), and its intact
        # version lets the caller's retry loop wait for the right dump
        # and report exactly what it saw.
        return params, {"source": "disk_raw", "version": v,
                        "load_s": time.monotonic() - t0}
    if want_version is not None:
        # pickle/HF always report version -1: they can NEVER satisfy a
        # pinned version, so skip their multi-GB deserialization instead
        # of paying it once per retry while waiting for the raw dump.
        return None, {"source": "no_raw_dump", "version": -1,
                      "load_s": time.monotonic() - t0}
    state_file = os.path.join(model_path, "engine_state.pkl")
    if os.path.exists(state_file):
        import pickle

        with open(state_file, "rb") as f:
            params = pickle.load(f)["params"]
        return params, {"source": "pickle", "version": -1,
                        "load_s": time.monotonic() - t0}
    from areal_tpu.models.hf import load_hf_model

    _, params = load_hf_model(model_path)
    return params, {"source": "hf", "version": -1,
                    "load_s": time.monotonic() - t0}


def load_for_serving(
    model_path: str,
    shm_dir: Optional[str] = None,
    want_version: Optional[int] = None,
    retries: Optional[int] = None,
    retry_s: Optional[float] = None,
) -> Tuple[Any, Dict[str, Any]]:
    """Load params for a generation server's weight update, fastest source
    first. Returns (params, info) where info records the source and load
    seconds for the /metrics surface:

    1. ``shm_dir`` raw dump      — same-host tmpfs fast path
    2. ``model_path`` raw dump   — mmap from page cache / NFS
    3. ``model_path`` pickle     — engine_state.pkl (checkpoint fallback)
    4. ``model_path`` HF dir     — cold start from an HF checkpoint

    With ``want_version`` set, the loaded dump's version must MATCH it.
    The pickle/HF fallbacks report version -1 and a raw dump can lag the
    publisher; accepting either would pin stale weights under the new
    version label, silently corrupting routing and the staleness gate.
    The chain itself is version-aware: a raw dump holding the wrong
    version falls through to the next source (a stale tmpfs copy must
    not shadow the matching NFS dump). A miss is retried (the dump may
    still be landing — cross-host NFS attribute caching can lag the
    publisher by seconds, and a pinned retry is just a manifest read
    since it skips the pickle/HF deserialization), then raised as
    :class:`WeightVersionMismatch` so the caller fails the update and
    eviction/readmission re-syncs the server instead. The default
    budget (``AREAL_WEIGHT_LOAD_RETRIES`` x ``AREAL_WEIGHT_LOAD_RETRY_S``,
    40 x 0.25 s = 10 s) matches the plane path's manifest-retry scale.
    """
    t0 = time.monotonic()
    if retries is None:
        retries = int(os.environ.get("AREAL_WEIGHT_LOAD_RETRIES", "40"))
    if retry_s is None:
        retry_s = float(os.environ.get("AREAL_WEIGHT_LOAD_RETRY_S", "0.25"))
    attempts = max(1, retries)
    last_info = None
    raw_seen: Dict[str, int] = {}
    for attempt in range(attempts):
        params, info = _load_once(
            model_path, shm_dir, t0,
            want_version=want_version, raw_seen=raw_seen,
        )
        if want_version is None or info["version"] == want_version:
            return params, info
        last_info = info
        if attempt < attempts - 1:
            time.sleep(retry_s)
    raise WeightVersionMismatch(
        f"requested weight version {want_version} but "
        + (
            "no raw dump was available"
            if last_info["source"] == "no_raw_dump"
            else f"{last_info['source']} dump holds version "
            f"{last_info['version']}"
        )
        + f" after {attempts} attempt(s) (model_path={model_path}"
        + (f", mismatched raw dumps seen: {raw_seen}" if raw_seen else "")
        + ")"
    )

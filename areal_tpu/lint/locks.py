"""Checker ``lock-order``: sync-lock discipline around event loops and
each other.

The PR 11/12 spill/drain machinery runs real threads
(``threading.Lock``) next to aiohttp event loops, which creates two
deadlock classes and one ordering class, all invisible to tests until
the exact interleaving lands:

- ``await`` while holding a sync lock: the coroutine parks WITH the
  lock held; any other coroutine on the same loop that wants the lock
  blocks the loop thread itself — instant single-thread deadlock.
  (``async with asyncio.Lock()`` is the legal spelling and is not
  flagged: ``AsyncWith`` is a different node.)
- a loop-door crossing under a sync lock: ``_run_on_loop(...)`` or
  ``asyncio.run_coroutine_threadsafe(...).result()`` BLOCKS on work
  the loop must run; if any loop callback takes the same lock, both
  sides wait forever.
- cyclic acquisition order: ``with self._a: with self._b:`` in one
  method and ``with self._b: with self._a:`` in another — classic
  AB/BA. The graph is per class, per module (attribute identity
  across modules is not decidable from the AST); edges through
  helper calls are out of scope and documented as such.

Lock attributes are discovered, not declared: any
``self.X = threading.Lock()/RLock()`` assignment — or a bare
``X = threading.Lock()`` at class body or module scope — makes ``X``
a sync lock for that class (module); a function-local lock stays
scoped to its function; ``with self._a, self._b:``
acquires left-to-right and records the same ordering edges as the
nested spelling. Nested ``def``/``lambda`` bodies under a ``with lock:`` are
NOT "under the lock" — they run later, so the walk stops at function
boundaries.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Set, Tuple

from areal_tpu.lint.common import Finding, Module

CHECKER = "lock-order"

_LOCK_CTORS = ("threading.Lock", "threading.RLock")
_DOOR_ATTRS = ("_run_on_loop",)


@dataclasses.dataclass
class LockConfig:
    door_attrs: Tuple[str, ...] = _DOOR_ATTRS


def default_config() -> LockConfig:
    return LockConfig()


def _is_lock_ctor(mod: Module, node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    dotted = mod.dotted_name(node.func)
    return dotted in _LOCK_CTORS


def _enclosing_class(mod: Module, node: ast.AST) -> Optional[str]:
    cur = mod.parent(node)
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            return cur.name
        cur = mod.parent(cur)
    return None


def _collect_locks(mod: Module) -> Dict[object, Set[str]]:
    """scope -> sync-lock names assigned a threading.Lock/RLock there.

    Scope is a class name for ``self.X`` (and class-body ``X = ...``)
    locks, ``None`` for true module-level names, or the enclosing
    function AST node for function-local names — a local lock must not
    leak into the module bucket, or an unrelated same-named ``with x:``
    elsewhere fails the gate spuriously. Regression notes: review
    finds, PR 13."""
    locks: Dict[object, Set[str]] = {}
    for node in mod.nodes:
        if not isinstance(node, ast.Assign) or not _is_lock_ctor(
            mod, node.value
        ):
            continue
        for t in node.targets:
            if (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
            ):
                cls = _enclosing_class(mod, node)
                locks.setdefault(cls, set()).add(t.attr)
            elif isinstance(t, ast.Name):
                cls = _enclosing_class(mod, node)
                fn = mod.enclosing_function(node)
                if fn is not None:
                    locks.setdefault(fn, set()).add(t.id)
                elif cls is not None:
                    # Class-body ``_lock = threading.Lock()`` (the
                    # name_resolve spelling) is read back as
                    # ``self._lock`` — file it under the class.
                    locks.setdefault(cls, set()).add(t.id)
                else:
                    locks.setdefault(None, set()).add(t.id)
    return locks


def _lock_id(mod: Module, expr: ast.AST,
             locks: Dict[object, Set[str]],
             cls: Optional[str],
             fn: Optional[ast.AST]) -> Optional[str]:
    """Identity of a with-item context if it is a known sync lock."""
    if (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
        and expr.attr in locks.get(cls, ())
    ):
        return f"{cls}.{expr.attr}"
    if isinstance(expr, ast.Name):
        if fn is not None and expr.id in locks.get(fn, ()):
            return f"{getattr(fn, 'name', '<lambda>')}.{expr.id}"
        if expr.id in locks.get(None, ()):
            return f"<module>.{expr.id}"
    return None


def _walk_stop_at_functions(root: ast.AST) -> Iterable[ast.AST]:
    """Like ast.walk over the With body, but closed functions/lambdas
    run later, not under the lock."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _is_blocking_door_call(mod: Module, node: ast.AST,
                           cfg: LockConfig) -> Optional[str]:
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in cfg.door_attrs:
        return f.attr
    # asyncio.run_coroutine_threadsafe(...).result()
    if (
        isinstance(f, ast.Attribute)
        and f.attr == "result"
        and isinstance(f.value, ast.Call)
        and mod.dotted_name(f.value.func) ==
        "asyncio.run_coroutine_threadsafe"
    ):
        return "run_coroutine_threadsafe(...).result"
    return None


def check(mod: Module, cfg: LockConfig) -> List[Finding]:
    locks = _collect_locks(mod)
    if not locks:
        return []
    findings: List[Finding] = []
    # (class, holder-lock) -> {(inner-lock, line)}
    edges: Dict[str, Dict[str, int]] = {}

    for w in mod.nodes:
        if not isinstance(w, ast.With):
            continue
        cls = _enclosing_class(mod, w)
        fn = mod.enclosing_function(w)
        held_ids: List[str] = []
        for item in w.items:
            hid = _lock_id(mod, item.context_expr, locks, cls, fn)
            if hid and hid not in held_ids:
                held_ids.append(hid)
        if not held_ids:
            continue
        # ``with self._a, self._b:`` acquires left-to-right — record the
        # same edges the nested spelling would, or the one-line form of
        # an AB/BA cycle is never seen. Regression note: review find,
        # PR 13.
        for a, b in zip(held_ids, held_ids[1:]):
            edges.setdefault(a, {}).setdefault(b, w.lineno)
        held = ", ".join(held_ids)
        fn_name = getattr(fn, "name", "<module>")
        for inner in _walk_stop_at_functions(w):
            if isinstance(inner, ast.Await):
                findings.append(Finding(
                    mod.rel, inner.lineno, CHECKER,
                    f"await while holding sync lock {held} "
                    f"({fn_name}): the coroutine parks with the "
                    f"lock held and any same-loop waiter deadlocks "
                    f"the loop — release first, or use asyncio.Lock",
                ))
            door = _is_blocking_door_call(mod, inner, cfg)
            if door is not None:
                findings.append(Finding(
                    mod.rel, inner.lineno, CHECKER,
                    f"{door} under sync lock {held} ({fn_name}): "
                    f"blocks on the loop while holding the lock — "
                    f"if any loop callback takes {held}, both "
                    f"sides wait forever; hop the door first, "
                    f"then lock",
                ))
            if isinstance(inner, ast.With):
                for item in inner.items:
                    other = _lock_id(mod, item.context_expr, locks, cls,
                                     fn)
                    if other and other not in held_ids:
                        edges.setdefault(held_ids[-1], {}).setdefault(
                            other, inner.lineno
                        )

    # -- AB/BA cycle detection over the per-module edge graph -----------
    def reachable(src: str) -> Set[str]:
        seen: Set[str] = set()
        work = list(edges.get(src, ()))
        while work:
            cur = work.pop()
            if cur in seen:
                continue
            seen.add(cur)
            work.extend(edges.get(cur, ()))
        return seen

    reported: Set[Tuple[str, str]] = set()
    for a, inners in sorted(edges.items()):
        for b, line in sorted(inners.items()):
            if a in reachable(b) and (b, a) not in reported:
                reported.add((a, b))
                findings.append(Finding(
                    mod.rel, line, CHECKER,
                    f"lock-order cycle: {a} -> {b} here, but {b} "
                    f"also reaches {a} elsewhere in this module — "
                    f"pick one global order and stick to it",
                ))
    return findings

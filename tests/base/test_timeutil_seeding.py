import numpy as np

from areal_tpu.base import seeding
from areal_tpu.base.timeutil import FrequencyControl, Timer


def test_frequency_step():
    fc = FrequencyControl(frequency_step=3)
    assert [fc.check() for _ in range(7)] == [False, False, True, False, False, True, False]


def test_frequency_initial_value():
    fc = FrequencyControl(frequency_step=100, initial_value=True)
    assert fc.check() is True
    assert fc.check() is False


def test_frequency_state_roundtrip():
    fc = FrequencyControl(frequency_step=3)
    fc.check()
    state = fc.state_dict()
    fc2 = FrequencyControl(frequency_step=3)
    fc2.load_state_dict(state)
    assert fc2.check() is False
    assert fc2.check() is True


def test_frequency_epoch():
    fc = FrequencyControl(frequency_epoch=2)
    assert fc.check(epochs=1) is False
    assert fc.check(epochs=1) is True


def test_seeding_deterministic():
    seeding.set_random_seed(123, "worker0")
    a = np.random.rand(3)
    seeding.set_random_seed(123, "worker0")
    b = np.random.rand(3)
    assert np.allclose(a, b)
    seeding.set_random_seed(123, "worker1")
    c = np.random.rand(3)
    assert not np.allclose(a, c)
    k1 = seeding.prng_key("gen")
    k2 = seeding.prng_key("gen")
    assert (np.asarray(k1) == np.asarray(k2)).all()


def test_seeding_state_roundtrip():
    """ISSUE 16: host RNG state checkpoints and restores — a recovered
    process continues the exact stream an uninterrupted one produces."""
    import random

    seeding.set_random_seed(7, "worker0")
    random.random()
    np.random.rand(2)
    state = seeding.state_dict()
    expect_np = np.random.rand(4)
    expect_py = [random.random() for _ in range(4)]
    # Perturb everything the snapshot covers...
    seeding.set_random_seed(99, "other")
    np.random.rand(10)
    # ...then restore and replay: identical continuation.
    seeding.load_state(state)
    assert seeding.get_seed() == 7
    assert np.allclose(np.random.rand(4), expect_np)
    assert [random.random() for _ in range(4)] == expect_py
    # The identity half restores too (shuffle seeds derive from it).
    seeding.load_state(state)
    assert seeding.get_shuffle_seed() == (
        7 + seeding._hash_key("worker0/shuffle")
    ) % (2**31)


def test_timer():
    t = Timer()
    with t.scope("a"):
        pass
    with t.scope("a"):
        pass
    assert t.totals["a"] >= 0

"""GSPMD partition rules: megatron-equivalent shardings by annotation.

Replaces the reference's hand-written tensor/sequence-parallel modules
(realhf/impl/model/parallelism/tensor_parallel/modules.py — Column/Row
parallel linears, parallel embedding, vocab-parallel CE) with
`PartitionSpec`s over the (data, fsdp, seq, tensor) mesh:

- attention qkv projections: column-parallel  -> output dim on `tensor`
- attention output proj:     row-parallel     -> input dim on `tensor`
- MLP gate/up:               column-parallel; down: row-parallel
- embedding + LM head:       vocab on `tensor` (vocab-parallel CE falls out
  of the sharded logits + psum XLA inserts for logsumexp)
- every weight's other big dim on `fsdp` (ZeRO-3-style param sharding);
  optimizer state inherits these specs (ZeRO-1/2)
- activations: rows on (data, fsdp), sequence dim on `seq` (context
  parallelism; megatron-SP's activation sharding falls out here too)

The reference's parameter-flattening + interval scatter/gather machinery
(flatten_param.py, csrc/interval_op) has no TPU counterpart by design:
resharding is `jax.device_put` between NamedShardings (see realloc.py).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Params = Dict[str, Any]


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def param_partition_spec(path: str, ndim: int) -> P:
    """PartitionSpec for one parameter, by pytree path.

    Layer-stacked params have a leading L axis (never sharded). Biases and
    norms are small: replicated.
    """
    name = path.split("/")[-1]
    if "embedding" in path:
        return P("tensor", "fsdp")  # [V, D]
    if path.startswith("head") or "/head/" in path or path == "head/weight":
        return P("fsdp", "tensor")  # [D, V] or [D, 1]
    if name in ("wq", "wk", "wv", "w_gate", "w_up", "w_in"):
        if ndim == 4:
            # MoE stacked experts [L, E, D, F]: expert parallelism —
            # E shards over the ZeRO/fsdp axis (the einsum dispatch
            # "tec,td->ecd" with tokens on (data,fsdp) and experts on
            # fsdp makes XLA emit the token all-to-all; DeepSeek-style
            # EP-over-DP without custom collectives), F stays
            # column-parallel on tensor.
            return P(None, "fsdp", None, "tensor")
        return P(None, "fsdp", "tensor")  # [L, D, out]: column parallel
    if name in ("wo", "w_down", "w_out"):
        if ndim == 4:
            return P(None, "fsdp", "tensor", None)  # [L, E, F, D]
        return P(None, "tensor", "fsdp")  # [L, in, D]: row parallel
    if name in ("bq", "bk", "bv", "b_gate", "b_up", "b_in"):
        return P(None, "tensor")  # [L, out]
    # norms, small biases (b_down/b_out [L, D]), router [L, D, E],
    # q_norm/k_norm: replicated.
    return P(*([None] * ndim))


def _moe_fsdp_fallback(name: str, ndim: int) -> Optional[P]:
    """When num_experts doesn't divide the fsdp axis, EP is impossible —
    but the expert weights are the bulk of model memory, so ZeRO-3 must
    not silently degrade to full replication: shard the hidden dim on
    fsdp instead."""
    if ndim != 4:
        return None
    if name in ("w_gate", "w_up"):
        return P(None, None, "fsdp", "tensor")  # [L, E, D, F]
    if name == "w_down":
        return P(None, None, "tensor", "fsdp")  # [L, E, F, D]
    return None


def _axis_size(mesh, entry) -> int:
    """Mesh-axis product for one PartitionSpec entry. ``mesh`` is a
    jax Mesh OR a plain ``{axis: size}`` mapping — the latter keeps the
    slice-resolution path (weight-plane shard manifests) usable without
    constructing devices."""
    if entry is None:
        return 1
    sizes = getattr(mesh, "shape", mesh)
    names = entry if isinstance(entry, tuple) else (entry,)
    size = 1
    for n in names:
        size *= sizes[n]
    return size


def fit_spec_to_shape(spec: P, shape, mesh: Mesh) -> P:
    """Drop sharded axes a dimension cannot honor (not divisible by the
    mesh-axis size — e.g. the critic head's [D, 1] output dim)."""
    entries = list(spec) + [None] * (len(shape) - len(spec))
    fitted = []
    for dim, entry in zip(shape, entries):
        fitted.append(entry if dim % _axis_size(mesh, entry) == 0 else None)
    return P(*fitted)


def fitted_param_spec(path: str, shape, mesh) -> P:
    """The PartitionSpec a parameter actually gets on this mesh: the
    megatron-style rule spec, fitted to the shape (indivisible axes
    dropped), with the MoE ZeRO fallback applied. ``mesh`` may be a jax
    Mesh or an ``{axis: size}`` mapping (see ``_axis_size``) — the
    SINGLE source of truth shared by ``param_shardings`` (device
    placement) and the weight plane's shard manifests (byte slicing),
    so what a shard manifest ships is exactly what the engine's
    NamedSharding will place."""
    spec = param_partition_spec(path, len(shape))
    fitted = fit_spec_to_shape(spec, shape, mesh)
    if len(spec) > 1 and spec[1] == "fsdp" and fitted[1] is None:
        # Expert dim indivisible by fsdp: fall back to hidden-dim
        # ZeRO sharding rather than replicating the expert weights.
        alt = _moe_fsdp_fallback(path.split("/")[-1], len(shape))
        if alt is not None:
            fitted = fit_spec_to_shape(alt, shape, mesh)
    return fitted


def param_shardings(params: Params, mesh: Mesh) -> Params:
    """Pytree of NamedShardings matching `params`' structure."""

    def one(path, leaf):
        return NamedSharding(
            mesh, fitted_param_spec(_path_str(path), leaf.shape, mesh)
        )

    return jax.tree_util.tree_map_with_path(one, params)


def spec_slices(spec: P, shape, axis_sizes, coords):
    """Per-dimension ``(start, stop)`` of one mesh coordinate's shard of
    a row-major array under ``spec`` — pure integer math, mirroring
    ``NamedSharding.devices_indices_map`` (tuple entries shard over the
    product with the FIRST named axis varying slowest).

    ``axis_sizes``: {axis: size}; ``coords``: {axis: coordinate}. The
    caller passes a spec already fitted to the shape
    (``fitted_param_spec``): every sharded dim must divide evenly."""
    entries = list(spec) + [None] * (len(shape) - len(spec))
    out = []
    for dim, entry in zip(shape, entries):
        size = _axis_size(axis_sizes, entry)
        if size == 1:
            out.append((0, int(dim)))
            continue
        if dim % size != 0:
            raise ValueError(
                f"dim {dim} not divisible by mesh extent {size} "
                f"for entry {entry!r} (spec not fitted?)"
            )
        names = entry if isinstance(entry, tuple) else (entry,)
        c = 0
        for n in names:
            c = c * axis_sizes[n] + coords[n]
        shard = dim // size
        out.append((c * shard, (c + 1) * shard))
    return out


def leaf_shard_slices(path: str, shape, axis_sizes, coords):
    """(start, stop) per dim of this mesh coordinate's shard of one
    parameter, by pytree path — fitted spec + slice math in one step."""
    return spec_slices(
        fitted_param_spec(path, shape, axis_sizes), shape, axis_sizes, coords
    )


def tensor_shard_slices(path: str, shape, degree: int, rank: int):
    """Shard slices for rank ``rank`` of a ``degree``-way TENSOR-parallel
    group (the serving-mesh case: every other axis is 1). Replicated
    leaves come back as full-extent slices — each rank fetches its own
    copy of norms/biases, the small +ε on top of payload/TP."""
    if degree < 1 or not (0 <= rank < degree):
        raise ValueError(f"bad tensor shard rank {rank}/{degree}")
    sizes = {"data": 1, "fsdp": 1, "seq": 1, "tensor": degree}
    coords = {"data": 0, "fsdp": 0, "seq": 0, "tensor": rank}
    return leaf_shard_slices(path, shape, sizes, coords)


def expert_shard_slices(path: str, shape, degree: int, rank: int):
    """Shard slices for rank ``rank`` of a ``degree``-way EXPERT-parallel
    group: stacked expert leaves ([L, E, ...] MoE weights, the bulk of
    an expert-dominated checkpoint) slice E ``degree`` ways; every other
    leaf comes back full-extent — each gserver fetches all attention /
    norm / router weights but only its OWN experts (ROADMAP item 5).
    An expert dim indivisible by ``degree`` degrades that leaf to
    full-extent (replicated) rather than slicing something else: the
    stream stays byte-correct, just without the 1/EP saving."""
    if degree < 1 or not (0 <= rank < degree):
        raise ValueError(f"bad expert shard rank {rank}/{degree}")
    spec = param_partition_spec(path, len(shape))
    if (
        len(shape) == 4 and len(spec) > 1 and spec[1] == "fsdp"
        and shape[1] % degree == 0
    ):
        sizes = {"data": 1, "fsdp": degree, "seq": 1, "tensor": 1}
        coords = {"data": 0, "fsdp": rank, "seq": 0, "tensor": 0}
        return spec_slices(P(None, "fsdp"), shape, sizes, coords)
    return [(0, int(d)) for d in shape]


def compose_shard_slices(a, b, shape):
    """Intersect two shard-slice lists that slice DISJOINT dims (e.g. a
    TP slice of F and an EP slice of E on the same [L, E, D, F] leaf).
    Per dim, at most one of the two may be a proper sub-slice."""
    out = []
    for (a0, a1), (b0, b1), dim in zip(a, b, shape):
        if (a0, a1) == (0, int(dim)):
            out.append((b0, b1))
        elif (b0, b1) == (0, int(dim)):
            out.append((a0, a1))
        else:
            raise ValueError(
                f"both shardings slice the same dim of {tuple(shape)}: "
                f"{(a0, a1)} vs {(b0, b1)}"
            )
    return out


def shard_params(params: Params, mesh: Mesh) -> Params:
    """Place a host pytree onto the mesh with megatron-equivalent sharding."""
    return jax.device_put(params, param_shardings(params, mesh))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """[R, T] token rows: rows over (data, fsdp), sequence over seq."""
    return NamedSharding(mesh, P(("data", "fsdp"), "seq"))


def activation_constraint(x, mesh: Mesh):
    """Constrain [R, T, D] activations inside jit."""
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(("data", "fsdp"), "seq", None))
    )


def logits_constraint(x, mesh: Mesh):
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(("data", "fsdp"), "seq", "tensor"))
    )


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())

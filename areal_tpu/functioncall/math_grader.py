"""Math answer verification: extraction + normalization + equivalence.

Counterpart of the reference's local math grader
(functioncall/math/function/grader.py:73-260 `math_equal`, and
realhf/impl/dataset/math_parser.py) built from scratch with the same
judging behavior:

- final-answer extraction (\\boxed{...}, "the answer is ...", last number)
- LaTeX normalization (fractions, roots, powers, text/units, spacing)
- multiple-choice cleaning (trailing "...the answer is (C)" -> "c")
- numeric equality at 1e-4 relative tolerance, with percentage
  equivalence (x == y, x/100 == y, x*100 == y)
- element-wise tuples/sets, interval answers incl. \\cup unions
  (bracket kinds must match, endpoints compared recursively)
- matrix answers (\\begin{pmatrix}/bmatrix), element-wise
- equation answers ("x = 5" vs "5"), \\pm expansion
- sympy symbolic equivalence as the last resort, run in a separate
  process with a hard timeout (sympy.simplify can hang; reference
  grader.py:337 call_with_timeout does the same)
"""

from __future__ import annotations

import multiprocessing
import re
from typing import Any, List, Optional, Tuple

# The forked child pays a cold sympy import + parse before simplify; on
# a loaded machine (full test suite, busy CI) 3s starves legitimate
# equivalences into False. AREAL_SYMPY_TIMEOUT_S widens the budget
# without touching the production default (tests/conftest.py sets it).
from areal_tpu.base import env_registry

SYMPY_TIMEOUT_S = env_registry.get_float("AREAL_SYMPY_TIMEOUT_S")
REL_TOL = 1e-4


# ---------------------------------------------------------------------------
# Extraction
# ---------------------------------------------------------------------------


def extract_boxed(text: str) -> Optional[str]:
    """Last \\boxed{...} / \\fbox{...} content, brace-aware."""
    best = None
    for m in re.finditer(r"\\(?:boxed|fbox)\s*\{", text):
        start = m.end()
        depth = 1
        i = start
        while i < len(text) and depth:
            if text[i] == "{":
                depth += 1
            elif text[i] == "}":
                depth -= 1
            i += 1
        if depth == 0:
            best = text[start : i - 1]
    return best


def extract_answer(text: str) -> Optional[str]:
    boxed = extract_boxed(text)
    if boxed is not None:
        return boxed
    # "The answer is X" patterns (commas allowed: "1,000,000"), else the
    # last number in the text.
    m = re.findall(
        r"(?:answer is|answer:)\s*([^\n;]+?)(?:\.\s|\.$|$)", text, re.IGNORECASE
    )
    if m:
        return m[-1].strip()
    nums = re.findall(r"-?\d+(?:\.\d+)?(?:/\d+)?", text)
    return nums[-1] if nums else None


def choice_clean(ans: str) -> Optional[str]:
    """Reduce a multiple-choice answer to its letter: "(C)", "C.", "c )"
    and trailing-choice phrasings all become "c"; None if not a choice."""
    s = ans.strip().rstrip(".").strip()
    m = re.fullmatch(r"\(?\s*([A-Ea-e])\s*\)?", s)
    if m:
        return m.group(1).lower()
    return None


# ---------------------------------------------------------------------------
# Normalization
# ---------------------------------------------------------------------------

_LATEX_STRIP = [
    (r"\\left\s*", ""), (r"\\right\s*", ""), (r"\\!", ""), (r"\\,", ""),
    (r"\\;", ""), (r"\\:", ""), (r"~", ""), (r"\\\$", ""), (r"\$", ""),
    (r"\\text\{([^}]*)\}", r"\1"),
    (r"\\mathrm\{([^}]*)\}", r"\1"), (r"\\mbox\{([^}]*)\}", r"\1"),
    (r"\\mathbf\{([^}]*)\}", r"\1"), (r"\\operatorname\{([^}]*)\}", r"\1"),
    (r"\\%", "%"),  # keep a bare % — _to_number reads it as a percentage
    (r"\\cdot", "*"), (r"\\times", "*"), (r"\\div", "/"),
    (r"\\pi", "pi"), (r"\\infty", "oo"), (r"\\infinity", "oo"),
    (r"dollars?", ""), (r"degrees?", ""),
    (r"\^\{\\circ\}", ""), (r"\^\\circ", ""), (r"\\circ", ""),
    (r"\\ ", " "),
]


def normalize_answer(ans: str) -> str:
    s = ans.strip()
    # Protect matrix row separators (\\) from the single-backslash rules.
    s = s.replace("\\\\", "\x00ROW\x00")
    for pat, rep in _LATEX_STRIP:
        s = re.sub(pat, rep, s)
    # \frac{a}{b} -> (a)/(b); \sqrt{a} -> sqrt(a); x^{y} -> x**(y)
    for _ in range(4):
        s = re.sub(r"\\[dt]?frac\{([^{}]*)\}\{([^{}]*)\}", r"((\1)/(\2))", s)
        s = re.sub(r"\\[dt]?frac(\d)(\d)", r"((\1)/(\2))", s)
        s = re.sub(r"\\sqrt\{([^{}]*)\}", r"sqrt(\1)", s)
        s = re.sub(r"\\sqrt(\d)", r"sqrt(\1)", s)
        s = re.sub(r"\^\{([^{}]*)\}", r"**(\1)", s)
    s = s.replace("^", "**")
    # keep matrix markers; everything else: braces -> parens
    parts = re.split(r"(\\(?:begin|end)\{(?:p|b)matrix\})", s)
    parts = [
        p if p.startswith("\\begin") or p.startswith("\\end")
        else p.replace("{", "(").replace("}", ")")
        for p in parts
    ]
    s = "".join(parts)
    s = re.sub(r"\\(?!(begin|end|cup|pm)\b)([a-zA-Z]+)", r"\2", s)
    s = s.replace("\x00ROW\x00", "\\\\")
    s = re.sub(r"\s+", "", s)
    s = s.rstrip(".").lstrip("+")
    # thousands separators: 1,234 / 1,000,000 -> digits (comma followed by
    # exactly three digits); bare pairs like "1,2" stay tuples
    if "(" not in s and "[" not in s:
        while re.search(r"\d,\d{3}(\D|$)", s):
            s = re.sub(r"(\d),(\d{3})(\D|$)", r"\1\2\3", s)
    return s.lower()


# ---------------------------------------------------------------------------
# Structured comparisons
# ---------------------------------------------------------------------------


def _to_number(s: str) -> Optional[float]:
    s = s.strip()
    pct = False
    if s.endswith("%"):
        pct = True
        s = s[:-1]
    try:
        v = float(s)
        return v / 100.0 if pct else v
    except ValueError:
        pass
    m = re.fullmatch(r"\(?\(?(-?\d+(?:\.\d+)?)\)?/\(?(-?\d+(?:\.\d+)?)\)?\)?", s)
    if m:
        denom = float(m.group(2))
        if denom != 0:
            v = float(m.group(1)) / denom
            return v / 100.0 if pct else v
    return None


def _numeric_equal(a: float, b: float) -> bool:
    return abs(a - b) <= REL_TOL * max(1.0, abs(a), abs(b))


def _numeric_equal_with_percent(a: float, b: float) -> bool:
    """Reference grader.py:101: a answer may be given as a percentage of
    the reference (or vice versa)."""
    return any(
        _numeric_equal(a, c) for c in (b, b / 100.0, b * 100.0)
    )


def _split_top_level_commas(s: str) -> List[str]:
    parts, depth, cur = [], 0, []
    for ch in s:
        if ch in "([":
            depth += 1
        elif ch in ")]":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur))
    return parts


def _parse_intervals(s: str) -> Optional[List[Tuple[str, str, str, str]]]:
    """"[2,5)\\cup(7,oo)" -> [("[", "2", "5", ")"), ...]; None if not an
    interval expression. Endpoints may contain balanced parens (e.g.
    normalized fractions)."""
    parts = re.split(r"\\cup|∪", s)
    out = []
    for p in parts:
        p = p.strip()
        if len(p) < 4 or p[0] not in "([" or p[-1] not in ")]":
            return None
        inner = _split_top_level_commas(p[1:-1])
        if len(inner) != 2 or not inner[0] or not inner[1]:
            return None
        out.append((p[0], inner[0], inner[1], p[-1]))
    return out if out else None


def _parse_matrix(s: str) -> Optional[List[List[str]]]:
    m = re.match(
        r"^\\begin\{(?:p|b)matrix\}(.*)\\end\{(?:p|b)matrix\}$", s, re.DOTALL
    )
    if not m:
        return None
    body = m.group(1)
    rows = [r for r in re.split(r"\\\\", body) if r.strip()]
    return [[c.strip() for c in row.split("&")] for row in rows]


def _strip_equation_lhs(s: str) -> str:
    """"x=5" -> "5" when the LHS is a bare variable."""
    m = re.match(r"^[a-z][a-z0-9_]{0,3}=(.+)$", s)
    return m.group(1) if m else s


def _expand_pm(s: str) -> Optional[Tuple[str, str]]:
    if "\\pm" in s:
        return s.replace("\\pm", "+", 1), s.replace("\\pm", "-", 1)
    if "±" in s:
        return s.replace("±", "+", 1), s.replace("±", "-", 1)
    return None


# ---------------------------------------------------------------------------
# Sympy symbolic equivalence (timeout-guarded subprocess)
# ---------------------------------------------------------------------------


def _sympy_equal_raw(a: str, b: str) -> bool:
    try:
        import sympy
        from sympy.parsing.sympy_parser import (
            implicit_multiplication_application,
            parse_expr,
            standard_transformations,
        )

        tf = standard_transformations + (implicit_multiplication_application,)
        ea = parse_expr(a, transformations=tf, evaluate=True)
        eb = parse_expr(b, transformations=tf, evaluate=True)
        if ea == eb:
            return True
        # numeric fallback before the expensive simplify
        try:
            if abs(float(ea.evalf()) - float(eb.evalf())) < 1e-6:
                return True
        except Exception:
            pass
        return bool(sympy.simplify(ea - eb) == 0)
    except Exception:
        return False


def _sympy_worker(a: str, b: str, q) -> None:
    q.put(_sympy_equal_raw(a, b))


def _sympy_equal_local(
    a: str, b: str, timeout: float = SYMPY_TIMEOUT_S
) -> bool:
    """Fork-per-call fallback path: sympy equivalence in a fresh child
    with a hard timeout — simplify() can hang on adversarial model
    outputs, and a stuck reward stalls the whole rollout pipeline
    (reference grader.py:337). Pays a cold sympy import every call; the
    pooled executor path amortizes that, but this MUST keep working
    standalone (no executor fleet in unit tests / small runs)."""
    ctx = multiprocessing.get_context("fork")
    q = ctx.Queue(1)
    p = ctx.Process(target=_sympy_worker, args=(a, b, q), daemon=True)
    try:
        p.start()
        p.join(timeout)
        if p.is_alive():
            p.terminate()
            p.join(1.0)
            return False
        return bool(q.get_nowait()) if not q.empty() else False
    except Exception:
        return False
    finally:
        if p.is_alive():
            p.kill()


def _sympy_equal(a: str, b: str, timeout: float = SYMPY_TIMEOUT_S) -> bool:
    """sympy equivalence, routed through the warm reward-executor pool
    when one is registered (functioncall/remote.py) so hot grading paths
    skip the cold fork+import; falls back to the local fork-per-call
    sandbox whenever no pool is registered, none is live, or the pooled
    job itself errors (an executor outage must degrade to slower
    grading, never to wrong grades)."""
    if len(a) > 400 or len(b) > 400:  # refuse adversarially long inputs
        return False
    from areal_tpu.functioncall import remote

    pool = remote.get_executor_pool()
    if pool is not None and pool.available():
        res = pool.submit(
            [{"kind": "sympy_equal", "a": a, "b": b}], timeout_s=timeout
        )[0]
        if res.get("ok"):
            return bool(res.get("equal"))
    return _sympy_equal_local(a, b, timeout)


# ---------------------------------------------------------------------------
# Top-level equivalence
# ---------------------------------------------------------------------------


def answers_equal(given: str, reference: str, tol: float = REL_TOL,
                  _depth: int = 0) -> bool:
    if _depth > 4:
        return False
    ng, nr = normalize_answer(str(given)), normalize_answer(str(reference))
    if not ng and not nr:
        return True
    if ng == nr:
        return True

    # multiple choice: compare letters when the reference is a choice
    cg, cr = choice_clean(str(given)), choice_clean(str(reference))
    if cr is not None and cg is not None:
        return cg == cr

    # numbers (incl. percentage equivalence)
    fg, fr = _to_number(ng), _to_number(nr)
    if fg is not None and fr is not None:
        return _numeric_equal_with_percent(fg, fr)

    # \pm expands to an unordered pair
    for s, other, flip in ((str(given), nr, False), (str(reference), ng, True)):
        pm = _expand_pm(s)
        if pm is not None:
            plus, minus = pm
            both = "(" + plus + "," + minus + ")"
            return answers_equal(
                both if not flip else other,
                other if not flip else both,
                tol, _depth + 1,
            )

    # intervals (bracket kinds must match; endpoints compared recursively).
    # Only binding when BOTH sides parse as intervals — "(1,2)" is also a
    # valid tuple, and a bare "1,2" reference must still match it below.
    ig, ir = _parse_intervals(ng), _parse_intervals(nr)
    if ig is not None and ir is not None:
        if len(ig) != len(ir):
            return False
        return all(
            lg == lr and hg == hr
            and answers_equal(ag, ar, tol, _depth + 1)
            and answers_equal(bg, br, tol, _depth + 1)
            for (lg, ag, bg, hg), (lr, ar, br, hr) in zip(ig, ir)
        )

    # matrices, element-wise; a matrix vs a tuple/list compares flattened
    # (reference grader.py:60 str_to_pmatrix upgrades "{1,2}" answers)
    mg, mr = _parse_matrix(ng), _parse_matrix(nr)
    if mg is not None and mr is not None:
        if len(mg) != len(mr) or any(
            len(a) != len(b) for a, b in zip(mg, mr)
        ):
            return False
        return all(
            answers_equal(a, b, tol, _depth + 1)
            for ra, rb in zip(mg, mr)
            for a, b in zip(ra, rb)
        )
    if (mg is None) != (mr is None):
        flat_m = [c for row in (mg or mr) for c in row]
        other = ng if mg is None else nr
        parts = [p for p in re.split(r"[(),\[\]]", other) if p]
        if len(parts) == len(flat_m):
            return all(
                answers_equal(a, b, tol, _depth + 1)
                for a, b in zip(flat_m, parts)
            )
        return False

    # equations: strip a bare-variable LHS from either side
    sg, sr = _strip_equation_lhs(ng), _strip_equation_lhs(nr)
    if (sg, sr) != (ng, nr):
        return answers_equal(sg, sr, tol, _depth + 1)

    # tuple/set-like answers: compare element-wise
    if ("," in ng) and ("," in nr):
        pg = [p for p in re.split(r"[(),\[\]]", ng) if p]
        pr = [p for p in re.split(r"[(),\[\]]", nr) if p]
        if len(pg) == len(pr) and pg:
            return all(
                answers_equal(x, y, tol, _depth + 1)
                for x, y in zip(pg, pr)
            )

    return _sympy_equal(ng, nr)


def compare_answers(ans: Optional[str], reference_answer: Any) -> bool:
    """Compare an already-extracted answer against the reference
    answer(s): list coercion, \\boxed{} unboxing of solution-form
    ground truth, and the equivalence rules of answers_equal. The ONE
    reference-normalization rule — every grading mode (text, PAL
    python execution) must route through it so identically-stored
    ground truth scores identically."""
    if ans is None:
        return False
    if isinstance(reference_answer, (list, tuple, set)):
        refs = list(reference_answer)
    else:  # str, int, float, ... — answers_equal str()s its inputs
        refs = [reference_answer]
    # Datasets often store ground truth in solution form ("\boxed{42}"):
    # unbox it so it compares against the extracted answer, not the
    # literal \boxed markup.
    refs = [
        b if (b := extract_boxed(str(r))) is not None else r for r in refs
    ]
    return any(answers_equal(ans, r) for r in refs)


def grade_answer(solution_text: str, reference_answer: Any) -> bool:
    """True if the final answer in `solution_text` matches the reference."""
    return compare_answers(
        extract_answer(str(solution_text)), reference_answer
    )

"""kernel_micro bench family (ISSUE 15 tentpole, bench front).

Tier-1 teeth: ``validate_bench.py`` must refuse kernel_micro records
that lack parity fields, show the optimized path slower than its
baseline, fail greedy parity on the decode-state A/B, or present
non-driver-verified numbers without the cpu_proxy/evidence=proxy
labels. Banking tests run the real phase bodies at their CPU shapes
and assert the banked attested records validate cleanly.

Time budget docstrings per test; the banked-record tests re-use one
phase run each (gae/paged/splash a few seconds of tiny jits; the
decode-state A/B runs two 2-layer engines — heaviest, but warm the
persistent XLA cache holds both arms' programs and the module stays
~10 s total).
"""

import importlib.util
import json
import os

import pytest

from areal_tpu.bench import bank, phases

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

pytestmark = pytest.mark.serial

KMICRO_PHASES = (
    "kernel_micro_gae",
    "kernel_micro_paged_decode",
    "kernel_micro_splash",
    "kernel_micro_decode_state",
)


def _load_validator():
    spec = importlib.util.spec_from_file_location(
        "validate_bench", os.path.join(REPO, "scripts", "validate_bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _case(**mut):
    c = {
        "name": "decode_b8_float",
        "baseline_impl": "xla",
        "optimized_impl": "kernel",
        "parity_max_rel": 2e-7,
        "timed": 1.0,
        "baseline_ms": 4.0,
        "optimized_ms": 1.0,
        "speedup": 4.0,
    }
    c.update(mut)
    return c


def _cases_value(case=None, **mut):
    val = {
        "cases": [case or _case()],
        "n_cases": 1.0,
        "best_speedup": 4.0,
        "cpu_proxy": 1.0,
        "evidence": "proxy",
    }
    val.update(mut)
    return val


def _rec(val, driver_verified=False, platform="cpu"):
    return {
        "status": "ok",
        "pass": "measure",
        "value": val,
        "attestation": {"platform": platform,
                        "driver_verified": driver_verified},
    }


def test_validator_teeth_for_kmicro_cases():
    """Case-level refusals: missing/failed parity, timed case without
    timings, optimized slower than baseline, empty case list. Time
    budget: milliseconds (pure dict validation)."""
    v = _load_validator()

    def problems(case=None, **mut):
        return v.validate_phase_value(
            "kernel_micro_paged_decode", _rec(_cases_value(case, **mut))
        )

    assert problems() == []
    # No cases at all: a kernel_micro record without cases measures
    # nothing.
    assert problems(cases=[])
    # Parity missing: a timing without its parity check is refused.
    c = _case()
    del c["parity_max_rel"]
    assert problems(c)
    # Parity diverged.
    assert problems(_case(parity_max_rel=1e-2))
    # Optimized slower than baseline: a regression is not evidence.
    assert problems(_case(optimized_ms=5.0))
    # Timed case lacking its timing keys.
    c = _case()
    del c["speedup"]
    assert problems(c)
    # Parity-only (timed=0) cases are legal without timings (the
    # interpret-mode arms off-TPU).
    ok = {k: _case()[k] for k in
          ("name", "baseline_impl", "optimized_impl", "parity_max_rel")}
    ok["timed"] = 0.0
    assert problems(ok) == []


def test_validator_teeth_for_proxy_labeling():
    """CPU-proxy labeling is cross-checked against the record's own
    attestation, both directions. Time budget: milliseconds."""
    v = _load_validator()

    def problems(val, dv):
        return v.validate_phase_value(
            "kernel_micro_paged_decode", _rec(val, driver_verified=dv,
                                              platform="tpu" if dv else "cpu")
        )

    # Non-verified record missing the labels: refused.
    unlabeled = _cases_value()
    del unlabeled["evidence"]
    assert problems(unlabeled, dv=False)
    bad = _cases_value(cpu_proxy=0.0)
    assert problems(bad, dv=False)
    # Verified record claiming proxy: also refused (conflation both
    # ways).
    proxy_on_tpu = _cases_value()
    assert problems(proxy_on_tpu, dv=True)
    ok_tpu = _cases_value(cpu_proxy=0.0)
    del ok_tpu["evidence"]
    assert problems(ok_tpu, dv=True) == []


def test_validator_teeth_for_decode_state():
    """Decode-state A/B refusals: token-parity failure, resident arm
    not below legacy transfers, delta path moving more bytes. Time
    budget: milliseconds."""
    v = _load_validator()

    def problems(**mut):
        val = {
            "token_parity_ok": 1.0,
            "h2d_per_block_resident": 2.0,
            "h2d_per_block_legacy": 5.0,
            "h2d_bytes_per_block_resident": 300.0,
            "h2d_bytes_per_block_legacy": 400.0,
            "gen_tps_resident": 100.0,
            "gen_tps_legacy": 90.0,
            "cpu_proxy": 1.0,
            "evidence": "proxy",
        }
        val.update(mut)
        return v.validate_phase_value(
            "kernel_micro_decode_state", _rec(val)
        )

    assert problems() == []
    assert problems(token_parity_ok=0.0)
    assert problems(h2d_per_block_resident=5.0)  # not below legacy
    assert problems(h2d_per_block_resident=6.0)
    assert problems(h2d_bytes_per_block_resident=900.0)
    incomplete = problems()
    # Schema: dropping any declared key is refused.
    val = {
        "token_parity_ok": 1.0,
        "h2d_per_block_resident": 2.0,
        "h2d_per_block_legacy": 5.0,
        "cpu_proxy": 1.0,
        "evidence": "proxy",
    }
    assert v.validate_phase_value("kernel_micro_decode_state", _rec(val))
    assert incomplete == []


def test_kmicro_phases_registered_as_daemon_defaults():
    """All four kernel_micro phases must sit in the DEFAULT phase set —
    that is what makes the next unattended TPU window measure them —
    and must NOT be proxy-pinned (a proxy phase runs its subprocess on
    JAX_PLATFORMS=cpu forever, which would defeat the point). Time
    budget: milliseconds."""
    defaults = {s.name for s in phases.default_phases()}
    for name in KMICRO_PHASES:
        assert name in defaults, f"{name} not a default daemon phase"
        spec = phases.get(name)
        assert not spec.proxy, f"{name} must not be CPU-pinned"
        assert not spec.headline


def _bank_and_validate(phase_name, fn, bank_dir):
    val = fn("measure")
    path = bank.write_record(
        bank.make_record(phase_name, "measure", "ok", value=val), bank_dir
    )
    with open(path) as f:
        rec = json.load(f)
    bank.validate_record(rec)
    assert rec["attestation"]["driver_verified"] is False
    assert rec["value"]["cpu_proxy"] == 1.0
    assert rec["value"]["evidence"] == "proxy"
    v = _load_validator()
    assert v.validate_phase_value(phase_name, rec) == []
    return rec


def test_gae_phase_banks_and_validates(tmp_path, monkeypatch):
    """Acceptance (GAE leg): a banked kernel_micro GAE record shows the
    scan-depth win (assoc over serial scan) with parity attached and
    validates. Time budget: ~10 s warm (tiny CPU jits + an 18 ms host
    loop)."""
    b = str(tmp_path / "bank")
    monkeypatch.setenv("AREAL_BENCH_BANK", b)
    from areal_tpu.bench.workloads import kernel_micro_gae_phase

    rec = _bank_and_validate("kernel_micro_gae", kernel_micro_gae_phase, b)
    case = rec["value"]["cases"][0]
    assert case["optimized_impl"] == "assoc"
    assert case["speedup"] > 1.0, "assoc did not beat the serial scan"
    assert case["assoc_depth"] < case["scan_depth"]
    assert rec["value"]["gae_auto_impl"] == "assoc"
    v = _load_validator()
    assert v.validate_bank_dir(b) == []


def test_paged_decode_and_splash_phases_bank(tmp_path, monkeypatch):
    """The paged-decode sweep (pow2 admit shapes, float + int8) and the
    splash parity case bank attested CPU-proxy records that validate.
    Time budget: ~15 s warm (tiny pools; splash runs ONE interpret
    case)."""
    b = str(tmp_path / "bank")
    monkeypatch.setenv("AREAL_BENCH_BANK", b)
    from areal_tpu.bench.workloads import (
        kernel_micro_paged_decode_phase, kernel_micro_splash_phase,
    )

    rec = _bank_and_validate(
        "kernel_micro_paged_decode", kernel_micro_paged_decode_phase, b
    )
    names = {c["name"] for c in rec["value"]["cases"]}
    assert {"decode_b2_float", "decode_b2_int8", "decode_b8_float"} <= names
    int8 = [c for c in rec["value"]["cases"] if c["name"].endswith("int8")]
    assert all("quant_max_rel_vs_float" in c for c in int8)

    rec2 = _bank_and_validate(
        "kernel_micro_splash", kernel_micro_splash_phase, b
    )
    case = rec2["value"]["cases"][0]
    assert case["timed"] == 0.0  # interpret-only off-TPU: parity, no timing
    assert case["parity_max_rel"] <= 1e-4


def test_decode_state_phase_banks_and_validates(tmp_path, monkeypatch):
    """Acceptance (decode leg): the A/B banks token parity + the
    per-block H2D reduction and validates. Time budget: ~5 s warm (two
    tiny engines; the persistent XLA cache holds their programs), ~40 s
    cold."""
    b = str(tmp_path / "bank")
    monkeypatch.setenv("AREAL_BENCH_BANK", b)
    from areal_tpu.bench.workloads import kernel_micro_decode_state_phase

    val = kernel_micro_decode_state_phase("measure")
    path = bank.write_record(
        bank.make_record("kernel_micro_decode_state", "measure", "ok",
                         value=val), b
    )
    with open(path) as f:
        rec = json.load(f)
    bank.validate_record(rec)
    v = _load_validator()
    assert v.validate_phase_value("kernel_micro_decode_state", rec) == []
    assert rec["value"]["token_parity_ok"] == 1.0
    assert (rec["value"]["h2d_per_block_resident"]
            < rec["value"]["h2d_per_block_legacy"])

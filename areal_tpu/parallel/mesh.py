"""Device mesh construction and device-partition allocation.

TPU-native replacement for the reference's process-topology + NCCL-group
machinery (realhf/base/topology.py grids, realhf/impl/model/comm/
global_comm.py): parallelism is expressed as a `jax.sharding.Mesh` with
axes (data, fsdp, seq, tensor) and GSPMD inserts the collectives. Device
*partitions* (disjoint sets of chips for generation vs training, the
reference's `sglang.dXpYmZ+dApBmC` decoupled allocation) are contiguous
slices of the device list, each carrying its own mesh.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from areal_tpu.base.topology import MeshSpec

MESH_AXES = ("data", "fsdp", "seq", "tensor")


def make_mesh(spec: MeshSpec, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a (data, fsdp, seq, tensor) mesh from a MeshSpec.

    Axis order puts `tensor` innermost so tensor-parallel collectives ride
    the fastest ICI links, matching megatron convention.
    """
    devices = list(devices) if devices is not None else jax.devices()
    if spec.size != len(devices):
        raise ValueError(
            f"mesh spec {spec} needs {spec.size} devices, got {len(devices)}"
        )
    arr = np.array(devices).reshape(spec.data, spec.fsdp, spec.seq, spec.tensor)
    return Mesh(arr, MESH_AXES)


def single_device_mesh(device: Optional[jax.Device] = None) -> Mesh:
    d = device or jax.devices()[0]
    return Mesh(np.array([d]).reshape(1, 1, 1, 1), MESH_AXES)


@dataclasses.dataclass
class DevicePartition:
    """A named slice of the global device list with its mesh spec."""

    name: str
    device_ids: List[int]  # indices into jax.devices()
    mesh_spec: MeshSpec

    def devices(self) -> List[jax.Device]:
        all_devices = jax.devices()
        return [all_devices[i] for i in self.device_ids]

    def make_mesh(self) -> Mesh:
        return make_mesh(self.mesh_spec, self.devices())


@dataclasses.dataclass
class AllocationMode:
    """Parsed allocation DSL (counterpart of the reference's
    `sglang.d4m1+d2m2`-style strings, realhf/experiments/common/utils.py:289).

    Forms:
    - "d2t4"             : one shared partition for everything (sync/global hybrid)
    - "gen.d4t1+d2t2"    : decoupled: first 4 devices generation, next 4 training
    """

    gen_spec: Optional[MeshSpec]
    train_spec: MeshSpec
    decoupled: bool

    @classmethod
    def parse(cls, s: str) -> "AllocationMode":
        s = s.strip()
        if "+" in s:
            gen_part, train_part = s.split("+", 1)
            if "." in gen_part:
                prefix, gen_part = gen_part.split(".", 1)
                if prefix not in ("gen", "sglang", "jax"):
                    raise ValueError(f"unknown allocation prefix {prefix!r} in {s!r}")
            return cls(
                gen_spec=MeshSpec.parse(gen_part),
                train_spec=MeshSpec.parse(train_part),
                decoupled=True,
            )
        return cls(gen_spec=None, train_spec=MeshSpec.parse(s), decoupled=False)

    def partitions(self, n_devices: Optional[int] = None) -> Dict[str, DevicePartition]:
        n = n_devices if n_devices is not None else len(jax.devices())
        need = self.train_spec.size + (self.gen_spec.size if self.decoupled else 0)
        if need > n:
            raise ValueError(f"allocation needs {need} devices, have {n}")
        out: Dict[str, DevicePartition] = {}
        cursor = 0
        if self.decoupled:
            out["gen"] = DevicePartition(
                "gen", list(range(cursor, cursor + self.gen_spec.size)), self.gen_spec
            )
            cursor += self.gen_spec.size
        out["train"] = DevicePartition(
            "train", list(range(cursor, cursor + self.train_spec.size)), self.train_spec
        )
        return out

#!/usr/bin/env python3
"""Summarize a jax.profiler trace into kernel-category stats.

Usage:
  python scripts/analyze_trace.py <trace.json[.gz] | profile dir> [--json]
  python scripts/analyze_trace.py /tmp/areal_tpu/traces/actor_train/step4

Reference counterpart: realhf/base/monitor.py:404-610 (CUDA kernel time
categories); see areal_tpu/utils/trace_analysis.py for the classifier.
"""

import argparse
import json
import sys

from areal_tpu.utils import trace_analysis as ta


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("path", help="trace file or dump directory")
    p.add_argument(
        "--json", action="store_true", help="emit machine-readable JSON"
    )
    p.add_argument(
        "--include-host",
        action="store_true",
        help="fall back to host lanes when the trace has no device lanes "
        "(CPU-only runs)",
    )
    p.add_argument("--top", type=int, default=15, help="top-k op listing")
    args = p.parse_args(argv)

    trace = ta.load_trace(args.path)
    stats = ta.analyze(trace, include_host=args.include_host)
    if not stats:
        print(
            "no device lanes found (CPU trace? try --include-host)",
            file=sys.stderr,
        )
        return 1
    agg = ta.aggregate(stats)
    pids = None if ta.device_lanes(trace) else []
    top = ta.top_ops(trace, pids=pids, k=args.top)
    if args.json:
        print(
            json.dumps(
                {
                    "aggregate": agg,
                    "per_device": [
                        {
                            "device": s.device,
                            "times_us": s.times_us,
                            "span_us": s.span_us,
                            "n_ops": s.n_ops,
                        }
                        for s in stats
                    ],
                    "top_ops": [
                        {
                            "name": n,
                            "category": c,
                            "total_us": us,
                            "count": cnt,
                        }
                        for n, c, us, cnt in top
                    ],
                }
            )
        )
    else:
        print(ta.format_report(stats, agg, top))
    return 0


if __name__ == "__main__":
    sys.exit(main())

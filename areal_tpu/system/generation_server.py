"""JAX generation server worker: the ServingEngine behind HTTP.

Counterpart of the reference's GenerationServer + patched SGLang
(realhf/system/generation_server.py:121, realhf/api/cli_args.py:323-391):
instead of launching an SGLang subprocess, the engine runs in-process on
this worker's TPU devices. The HTTP surface mirrors what the rest of the
stack expects (SURVEY §8 "SGLang server contract"):

- POST /generate {qid, input_ids, gconfig...} -> token-in/token-out with
  logprobs and version stamps
- POST /update_weights_from_disk {model_path, allow_interrupt}
- GET  /metrics  (areal:num_used_tokens / areal:num_running_reqs)
- GET  /health

Disaggregated prefill/decode serving (docs/serving.md): the server has
a live ``role`` (prefill / decode / unified, starting from the config,
flipped at runtime by the manager's elastic sizer via POST /set_role).
When the manager pairs a decode server into a request (``decode_url``
in the /generate body), this server runs the prompt to its FIRST
sampled token only, exports the filled KV pages as a hash-indexed blob
(engine/kv_handoff.py), and POSTs /kv_handoff to the decode server —
which pulls the payload back over chunked HTTP (per-chunk sha256 +
Range resume, the weight-plane transfer discipline), imports it, and
runs the decode stream as a priority-0 continuation. Any handoff
failure falls back to serving the remainder locally, so disaggregation
can only add throughput, never lose a rollout.

Plus the streaming weight-distribution plane (system/weight_plane.py):

- POST /distribute_weights  prefetch version-N chunks into host memory
  from an ordered upstream list (fanout-tree parent, surviving peers,
  origin) WHILE still serving version N-1
- POST /cutover_weights     short interrupt + device-swap to the
  prefetched version; duration measured separately from transfer
- GET  /weights/manifest, /weights/chunk   serve held chunks to sibling
  servers (the peer hop that keeps trainer egress O(1))
"""

from __future__ import annotations

import asyncio
import collections
import os
import threading
import time
from typing import Any, Dict, Optional

from aiohttp import web

from areal_tpu.api import data_api
from areal_tpu.api.system_api import GenerationServerConfig
from areal_tpu.base import constants, logging, name_resolve, names, network, rpc, seeding, tracing
from areal_tpu.base.fault_injection import faults
from areal_tpu.engine.serving import GenRequest, ServingEngine
from areal_tpu.engine.weight_client import ChunkStore, assemble_params
from areal_tpu.system.weight_plane import (
    serve_store_chunk,
    serve_store_manifest,
)
from areal_tpu.system.worker_base import PollResult, Worker

logger = logging.getLogger("generation_server")


class GenerationServer(Worker):
    def _configure(self, config: GenerationServerConfig):
        self.cfg = config
        constants.set_experiment_trial_names(
            config.experiment_name, config.trial_name
        )
        seeding.set_random_seed(config.seed, config.worker_name)
        import areal_tpu.engine.factories  # noqa: F401  (registry)
        from areal_tpu.api.model_api import make_model

        # One shared model name across the fleet: the init rng folds the
        # name in, so a per-index name would give every random-init
        # server DIFFERENT weights — fatal for disaggregation, where KV
        # prefilled on one server is decoded on another (checkpoint
        # loads were never affected; random init is the test/bench
        # path).
        kwargs: Dict[str, Any] = {"name": "gserver"}
        if config.model_path is not None:
            kwargs["model_path"] = config.model_path
        if config.tokenizer_path is not None:
            kwargs["tokenizer_path"] = config.tokenizer_path
        model = make_model(config.model, **kwargs)
        raw = model._raw
        self.tokenizer = model.tokenizer
        eos = self.tokenizer.eos_token_id if self.tokenizer else None
        from areal_tpu.engine.serving import serving_mesh

        mesh = (
            serving_mesh(config.tensor_parallel)
            if config.tensor_parallel > 1
            else None
        )
        self.engine = ServingEngine(
            cfg=raw["cfg"],
            params=raw["params"],
            max_batch_size=config.max_concurrent_requests,
            max_seq_len=config.max_seq_len,
            decode_block_steps=config.decode_block_steps,
            eos_token_id=eos,
            seed=config.seed + config.server_index,
            page_size=config.kv_page_size,
            kv_pool_tokens=config.kv_pool_tokens,
            prompt_bucket=config.prompt_bucket,
            prefill_max_batch=config.prefill_max_batch,
            prefill_chunk=config.prefill_chunk,
            chunked_prefill_per_lap=config.chunked_prefill_per_lap,
            prefix_cache_tokens=config.prefix_cache_tokens,
            kv_cache_dtype=config.kv_cache_dtype,
            speculative_draft_len=config.speculative_draft_len,
            speculative_ngram=config.speculative_ngram,
            speculative_window=config.speculative_window,
            decode_weight_dtype=config.decode_weight_dtype,
            prefill_token_budget=config.prefill_token_budget,
            decode_blocks_per_admit=config.decode_blocks_per_admit,
            kv_tier_bytes=config.kv_tier_bytes,
            kv_tier_disk_dir=config.kv_tier_disk_dir,
            kv_tier_disk_bytes=config.kv_tier_disk_bytes,
            kv_spill_dtype=config.kv_spill_dtype,
            mesh=mesh,
        )
        self.engine.start()
        if config.warm_on_start:
            # Compile the serving programs before taking traffic (and
            # before discovery registration below): one bucket's worth
            # of prompt + the decode block covers the hot path.
            self.engine.warm([config.prompt_bucket])
        self._n_interrupted = 0
        self._n_shed = 0
        self._last_load_info = None

        # Disaggregated serving: live pool role (the manager's elastic
        # sizer re-roles "unified"-configured servers at runtime) + the
        # export stash the decode side pulls handoff payloads from.
        if config.role not in ("unified", "prefill", "decode"):
            raise ValueError(
                f"role must be unified/prefill/decode, got {config.role!r}"
            )
        self.role = config.role
        self._role_lock = threading.Lock()
        # Drain-then-leave (docs/fault_tolerance.md): once draining,
        # admission sheds every new /generate with 429 (the manager
        # already stopped routing here), in-flight work finishes, the
        # parked prefixes migrate to peers over the /kv wire, and the
        # worker departs with a graceful heartbeat stop. _draining is a
        # plain bool flipped on the HTTP loop and read by the poll
        # thread (GIL-atomic); _drain_state is mutated only by the
        # drain task on the HTTP loop.
        self._draining = False
        self._drain_state: Dict[str, Any] = {
            "draining": False, "done": False, "held": 0, "migrated": 0,
            "lost": 0, "stale_dropped": 0, "drain_ms": 0.0, "reason": "",
        }
        # Drain-migration ingest counters (/kv/accept).
        self._kv_accepted = 0
        self._kv_accept_bytes = 0
        self._handoff_store: "collections.OrderedDict[str, tuple]" = (
            collections.OrderedDict()
        )
        self._handoff_ok = 0
        self._handoff_failed = 0
        self._handoff_fallback = 0
        self._last_handoff_ms = 0.0
        self._last_kv_transfer_ms = 0.0
        self._handoff_session = None  # lazy aiohttp session (HTTP loop)
        # Tiered KV plane (docs/serving.md): peer-pull counters — a
        # returning session routed here without its prefix pulls it
        # from whichever peer the manager's global index names.
        self._kv_peer_hits = 0
        self._kv_peer_bytes = 0
        self._kv_peer_failed = 0
        self._last_kv_restore_ms = 0.0
        self._kv_manifests_served = 0
        self._kv_chunks_served = 0
        self._kv_chunk_bytes_served = 0

        # Shard-aware weight plane: this server's coordinates in a
        # fleet-level tensor-parallel group (None = fetch full
        # payloads). The manager groups fanout trees by this spec —
        # only same-shard peers hold the same chunk stream.
        rank, degree = config.weight_shard_rank, config.weight_shard_degree
        if (rank is None) != (degree is None):
            raise ValueError(
                "weight_shard_rank and weight_shard_degree must be set "
                f"together (got {rank!r}/{degree!r})"
            )
        if degree is not None and not (degree >= 1 and 0 <= rank < degree):
            raise ValueError(f"bad weight shard {rank}/{degree}")
        self._weight_shard = (
            (int(rank), int(degree)) if degree is not None else None
        )
        if self._weight_shard is not None and degree > 1:
            # Fail at STARTUP, not after a full fleet transfer: a sliced
            # cutover can only land when this process hosts exactly the
            # mesh slice for its rank. A single-process mesh owns every
            # tensor coordinate, so sliced fetch needs a multi-host
            # (jax.distributed) deployment.
            t_size = (
                self.engine.mesh.shape.get("tensor", 1)
                if self.engine.mesh is not None else 1
            )
            if t_size != degree:
                raise ValueError(
                    f"weight_shard {rank}/{degree} requires a tensor "
                    f"mesh of extent {degree} (engine has {t_size}); "
                    f"set tensor_parallel={degree}"
                )
            coords = set(
                self.engine._addressable_tensor_coords().values()
            )
            if coords != {int(rank)}:
                raise ValueError(
                    f"weight_shard {rank}/{degree} requires this "
                    f"process to host exactly tensor coordinate {rank} "
                    f"of the mesh, but it hosts {sorted(coords)} — "
                    f"sliced weight fetch needs a multi-host "
                    f"(jax.distributed) mesh, one rank per server "
                    f"process"
                )

        # Weight-plane prefetch state machine: idle -> fetching -> ready
        # (-> failed). The store outlives its own cutover so this server
        # keeps serving chunks to later-wave siblings and to chaos
        # re-fanouts; a new /distribute_weights replaces it.
        self._wp_lock = threading.Lock()
        self._wp_store: Any = None
        self._wp_state = "idle"
        self._wp_transfer_ms = 0.0
        self._wp_verify_ms = 0.0
        self._wp_cutover_ms = 0.0
        self._wp_bytes_from_origin = 0
        self._wp_bytes_from_peers = 0
        self._wp_chunks_served = 0
        self._wp_bytes_served = 0
        # Shard-aware expectations for /metrics: a sliced fetch is
        # complete at its SHARD bytes — dashboards must divide ingress
        # by this, not the full payload, or every sliced fetch reads as
        # a torn transfer.
        self._wp_expected_bytes = 0
        self._wp_ingress_eq = 0.0
        self._wp_wire = "raw"

        # HTTP server on its own thread + loop.
        self._http_loop = asyncio.new_event_loop()
        self._http_ready = threading.Event()
        self._http_thread = threading.Thread(target=self._serve_http, daemon=True)
        self._http_thread.start()
        if not self._http_ready.wait(30):
            raise RuntimeError("generation server HTTP failed to start")

        # Register for discovery.
        name_resolve.add_subentry(
            names.gen_servers(config.experiment_name, config.trial_name),
            self.address,
        )
        name_resolve.add(
            names.gen_server_url(
                config.experiment_name, config.trial_name, str(config.server_index)
            ),
            self.address,
            keepalive_ttl=60,
            replace=True,
        )
        logger.info(f"generation server {config.server_index} at {self.address}")

    def _heartbeat_payload(self):
        # The gserver manager maps health members -> routing-table URLs
        # through this field (eviction on missed beats, readmission +
        # weight re-sync on return).
        payload = super()._heartbeat_payload()
        payload["url"] = self.address
        payload["server_index"] = self.cfg.server_index
        payload["role"] = self.role
        if self.cfg.model_id:
            # Multi-model fleets pool servers by this field; the
            # manager QUARANTINES a beat naming an unregistered id
            # rather than adopt it (system/model_registry.py).
            payload["model_id"] = self.cfg.model_id
        # The drain flag rides the heartbeat so even a RESTARTED
        # manager learns in-progress drains without asking.
        payload["draining"] = bool(self._draining)
        if self._weight_shard is not None:
            # (rank, degree): the manager plans per-shard fanout groups
            # from this.
            payload["weight_shard"] = list(self._weight_shard)
        return payload

    # ------------------------------------------------------------------
    # HTTP
    # ------------------------------------------------------------------

    def _serve_http(self):
        asyncio.set_event_loop(self._http_loop)
        app = web.Application()
        app.router.add_post("/generate", self._h_generate)
        app.router.add_post("/kv_handoff", self._h_kv_handoff)
        app.router.add_get("/kv_handoff/blob", self._h_kv_blob)
        app.router.add_get("/kv/manifest", self._h_kv_manifest)
        app.router.add_get("/kv/chunk", self._h_kv_chunk)
        app.router.add_get("/kv/index", self._h_kv_index)
        app.router.add_post("/kv/accept", self._h_kv_accept)
        app.router.add_post("/drain", self._h_drain)
        app.router.add_get("/drain", self._h_drain_status)
        app.router.add_post("/set_role", self._h_set_role)
        app.router.add_post("/configure", self._h_configure)
        app.router.add_post("/update_weights_from_disk", self._h_update_weights)
        app.router.add_post("/distribute_weights", self._h_distribute_weights)
        app.router.add_post("/cutover_weights", self._h_cutover_weights)
        app.router.add_get("/weights/manifest", self._h_weights_manifest)
        app.router.add_get("/weights/chunk", self._h_weights_chunk)
        app.router.add_get("/metrics", self._h_metrics)
        app.router.add_get("/health", self._h_health)
        runner = web.AppRunner(app)
        self._http_loop.run_until_complete(runner.setup())
        host = network.gethostip()
        port = network.find_free_port()
        site = web.TCPSite(runner, host, port)
        self._http_loop.run_until_complete(site.start())
        self.address = f"http://{host}:{port}"
        self._http_ready.set()
        self._http_loop.run_forever()

    def _admission_overloaded(self) -> Optional[float]:
        """Backpressure watermark check: returns the Retry-After seconds
        when /generate must shed, None when the request may queue. Reads
        only host counters the engine maintains — no device sync."""
        cfg = self.cfg
        if self._draining:
            # Quiesce: the manager stopped routing here; stragglers
            # (in-flight schedule decisions, stale affinity) get the
            # normal shed treatment and retry elsewhere.
            return cfg.shed_retry_after_s
        depth_wm = cfg.max_queue_depth
        token_wm = cfg.max_queued_tokens
        if depth_wm is None and token_wm is None:
            return None
        over = (
            depth_wm is not None and self.engine.queue_depth >= depth_wm
        ) or (
            token_wm is not None
            and self.engine.queued_prompt_tokens >= token_wm
        )
        return cfg.shed_retry_after_s if over else None

    async def _h_generate(self, request: web.Request) -> web.Response:
        # Chaos injection point: tests arm this to kill/fail/stall THIS
        # server mid-rollout and prove clients fail over.
        await faults.maybe_fail_async("gserver.generate")
        d = await request.json()
        # Propagated deadline (base/rpc.py wire rule): a request whose
        # budget already expired is refused CHEAPLY — prefilling tokens
        # the caller will never consume just steals budget from live
        # requests. 429 + Retry-After 0: the client re-mints a budget
        # on its next attempt.
        deadline = rpc.Deadline.from_headers(request.headers)
        if deadline is not None and deadline.expired():
            rpc.stats.incr("deadline_expired")
            return web.json_response(
                {"qid": str(d.get("qid", "")), "error": "deadline expired",
                 "retry_after": 0.0},
                status=429, headers={"Retry-After": "0"},
            )
        # Admission control BEFORE the engine sees the request: beyond
        # the queue-depth/token watermark the server load-sheds with 429
        # so open-loop tail latency stays bounded (clients back off with
        # jitter and the manager spills the session to another server).
        retry_after = self._admission_overloaded()
        if retry_after is not None:
            self._n_shed += 1
            tracing.event(
                "server.load_shed", ctx=tracing.extract_from(d),
                qid=str(d.get("qid", "")),
                queue_depth=self.engine.queue_depth,
            )
            return web.json_response(
                {
                    "qid": str(d.get("qid", "")),
                    "error": "overloaded",
                    "retry_after": retry_after,
                    "queue_depth": self.engine.queue_depth,
                },
                status=429,
                headers={"Retry-After": str(max(1, int(-(-retry_after // 1))))},
            )
        # Request-scoped tracing: the client's chunk span is this span's
        # parent, so the merged timeline shows queue+compute time on the
        # server track inside the client's chunk.
        gen_span = tracing.start_span(
            "server.generate",
            ctx=tracing.extract_from(d),
            qid=str(d.get("qid", "")),
            prompt_len=len(d.get("input_ids") or []),
        )
        # Tiered-KV restore (docs/serving.md): a returning session
        # routed here without its parked prefix restores it from the
        # local host/disk tier — or pulls it from the peer the
        # manager's global prefix index named (``kv_source``) — BEFORE
        # submission, so admission sees a parked prefix and prefills
        # only the delta. Any failure degrades to the full re-prefill
        # this path exists to avoid; it can never fail the request.
        await self._maybe_restore_prefix(d, deadline=deadline)
        g = d.get("gconfig", {})
        # Disaggregated path: the manager paired a decode server into
        # this request — prefill to the first token here, hand the KV
        # off, let the decode server run the stream. Single-token
        # budgets and self-pairings serve locally.
        decode_url = d.get("decode_url") or None
        if (
            decode_url
            and decode_url != self.address
            and int(g.get("max_new_tokens", 256)) > 1
        ):
            return await self._h_generate_disagg(
                d, g, decode_url, gen_span, deadline=deadline
            )
        req = self._gen_request_from(d, g)
        try:
            res = await self._submit_and_wait(req)
        except RuntimeError as e:
            # Fail-fast path: the serve loop already died; keep the same
            # JSON error contract as the in-flight res.error branch below.
            if gen_span is not None:
                gen_span.end(error=str(e))
            return web.json_response(
                {"qid": req.qid, "error": str(e)}, status=500
            )
        if gen_span is not None:
            gen_span.end(
                n_tokens=len(res.output_ids),
                interrupted=res.interrupted,
                version_start=res.version_start,
                version_end=res.version_end,
                error=res.error or "",
            )
        if res.error is not None:
            # Serve-loop death: surface as a 500 so clients retry against
            # another server instead of treating it as an empty completion.
            return web.json_response(
                {"qid": res.qid, "error": res.error}, status=500
            )
        if res.interrupted:
            self._n_interrupted += 1
        return web.json_response(self._gen_response(res))

    def _gen_request_from(self, d: Dict, g: Dict) -> GenRequest:
        return GenRequest(
            qid=str(d["qid"]),
            input_ids=[int(t) for t in d["input_ids"]],
            max_new_tokens=int(g.get("max_new_tokens", 256)),
            min_new_tokens=int(g.get("min_new_tokens", 0)),
            greedy=bool(g.get("greedy", False)),
            temperature=float(g.get("temperature", 1.0)),
            top_p=float(g.get("top_p", 1.0)),
            top_k=int(g.get("top_k", -1)),
            stop_token_ids=tuple(g.get("stop_token_ids", [])),
            priority=int(d.get("priority", 1)),
        )

    async def _submit_and_wait(self, req: GenRequest):
        """Submit to the engine, await the result on this event loop.
        Raises RuntimeError when the serve loop is already dead."""
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()

        def done_cb(res):
            loop.call_soon_threadsafe(
                lambda: fut.set_result(res) if not fut.done() else None
            )

        req.done_cb = done_cb
        self.engine.submit(req)
        return await fut

    @staticmethod
    def _gen_response(res, **extra) -> Dict:
        out = {
            "qid": res.qid,
            "output_ids": res.output_ids,
            "output_logprobs": res.output_logprobs,
            "no_eos": res.no_eos,
            "interrupted": res.interrupted,
            "version_start": res.version_start,
            "version_end": res.version_end,
            "latency": res.latency,
        }
        out.update(extra)
        return out

    # ------------------------------------------------------------------
    # Disaggregated prefill/decode (docs/serving.md)
    # ------------------------------------------------------------------

    async def _handoff_sess(self) -> "aiohttp.ClientSession":
        import aiohttp

        if self._handoff_session is None or self._handoff_session.closed:
            self._handoff_session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=600)
            )
        return self._handoff_session

    def _stash_handoff(self, qid: str, meta: Dict, payload: bytes):
        """Park an exported blob for the decode server's chunked pull.
        An entry lives until its /kv_handoff POST returns — which spans
        the decode server's WHOLE decode stream, not just the pull — so
        the cap must cover the server's full admission concurrency or
        normal load evicts in-flight blobs (404 on the pull -> handoff
        counted failed -> local fallback, silently un-disaggregating
        the fleet). TTL pruning handles decode servers that died
        mid-pull."""
        now = time.monotonic()
        self._handoff_store[qid] = (meta, payload, now)
        for k in [
            k for k, (_, _, t) in self._handoff_store.items()
            if now - t > 600.0
        ]:
            self._handoff_store.pop(k, None)
        cap = max(32, 4 * self.cfg.max_concurrent_requests)
        while len(self._handoff_store) > cap:
            self._handoff_store.popitem(last=False)

    async def _h_generate_disagg(self, d, g, decode_url, gen_span,
                                 deadline=None):
        from areal_tpu.engine.kv_handoff import KVHandoffError

        qid = str(d["qid"])
        budget = int(g.get("max_new_tokens", 256))
        min_new = int(g.get("min_new_tokens", 0))
        # Prefill leg: run to the first sampled token only. The finish
        # parks the prompt's KV pages under this qid (prefix cache).
        first_req = self._gen_request_from(d, g)
        first_req.max_new_tokens = 1
        first_req.min_new_tokens = min(1, min_new)
        try:
            res = await self._submit_and_wait(first_req)
        except RuntimeError as e:
            if gen_span is not None:
                gen_span.end(error=str(e))
            return web.json_response({"qid": qid, "error": str(e)}, status=500)
        if res.error is not None:
            if gen_span is not None:
                gen_span.end(error=res.error)
            return web.json_response(
                {"qid": qid, "error": res.error}, status=500
            )
        if res.interrupted or not res.output_ids or not res.no_eos:
            # Interrupted (client resubmits), zero-budget degenerate, or
            # the first token already hit EOS: nothing to hand off.
            if res.interrupted:
                self._n_interrupted += 1
            if gen_span is not None:
                gen_span.end(
                    n_tokens=len(res.output_ids),
                    interrupted=res.interrupted, disagg="short-circuit",
                )
            return web.json_response(self._gen_response(res))
        first = int(res.output_ids[0])
        t_handoff0 = time.monotonic()

        # Export the KV blob (engine-thread gather via the loop door).
        exp_span = tracing.start_span(
            "server.kv_export", ctx=tracing.extract_from(d),
            qid=qid, decode_url=decode_url,
        )
        meta = payload = None
        try:
            meta, payload = await asyncio.get_running_loop().run_in_executor(
                None,
                lambda: self.engine.export_kv_handoff(
                    qid, compress=self.cfg.kv_handoff_compress
                ),
            )
        except (KeyError, KVHandoffError, RuntimeError, TimeoutError) as e:
            # Short prompt (< one page), pool pressure evicted the park,
            # or the loop door timed out: serve the remainder locally.
            logger.warning(f"{qid}: kv export unavailable ({e!r}); "
                           f"serving remainder locally")
            if exp_span is not None:
                exp_span.end(error=repr(e))
            return await self._disagg_local_remainder(
                d, g, res, first, gen_span, reason=f"export: {e!r}"
            )
        if exp_span is not None:
            exp_span.end(
                n_tokens=meta["n_tokens"], bytes=len(payload),
                export_ms=self.engine.last_kv_export_ms,
            )
        # Mid-handoff chaos point: a prefill server dying HERE leaves
        # the client's /generate hanging on a dead socket — the failover
        # path (failed_server_url -> eviction -> reroute) must absorb it.
        await faults.maybe_fail_async("gserver.kv_export")
        self._stash_handoff(qid, meta, payload)
        try:
            sess = await self._handoff_sess()
            # The decode hop inherits the rollout's REMAINING budget
            # (base/rpc.py wire rule), so its blob pull-back can never
            # out-wait the client that asked for it.
            hop_headers = (
                deadline.headers() if deadline is not None else {}
            )
            async with sess.post(
                f"{decode_url}/kv_handoff",
                headers=hop_headers,
                json=tracing.inject_ctx_into(
                    {
                        "qid": qid,
                        "meta": meta,
                        "source": self.address,
                        "first_token": first,
                        "gconfig": {
                            "max_new_tokens": budget - 1,
                            "min_new_tokens": max(0, min_new - 1),
                            "greedy": bool(g.get("greedy", False)),
                            "temperature": float(g.get("temperature", 1.0)),
                            "top_p": float(g.get("top_p", 1.0)),
                            "top_k": int(g.get("top_k", -1)),
                            "stop_token_ids": list(g.get("stop_token_ids", [])),
                        },
                    },
                    gen_span.ctx if gen_span is not None else None,
                ),
            ) as r:
                body = await r.json()
                ok = r.status == 200 and "output_ids" in body
        except Exception as e:
            ok, body = False, {"error": repr(e)}
        finally:
            self._handoff_store.pop(qid, None)
        if not ok:
            self._handoff_failed += 1
            logger.warning(
                f"{qid}: kv handoff to {decode_url} failed "
                f"({str(body.get('error'))[:200]}); serving remainder locally"
            )
            return await self._disagg_local_remainder(
                d, g, res, first, gen_span,
                reason=f"decode: {str(body.get('error'))[:120]}",
            )
        self._handoff_ok += 1
        self._last_handoff_ms = (time.monotonic() - t_handoff0) * 1000.0
        if gen_span is not None:
            gen_span.end(
                n_tokens=1 + len(body["output_ids"]),
                disagg="handoff", decode_url=decode_url,
                handoff_ms=self._last_handoff_ms,
            )
        return web.json_response({
            "qid": qid,
            "output_ids": [first] + [int(t) for t in body["output_ids"]],
            "output_logprobs": (
                res.output_logprobs
                + [float(x) for x in body["output_logprobs"]]
            ),
            "no_eos": bool(body["no_eos"]),
            "interrupted": bool(body["interrupted"]),
            "version_start": res.version_start,
            "version_end": int(body["version_end"]),
            "latency": time.monotonic() - (t_handoff0 - res.latency),
            "disagg": {
                "decode_url": decode_url,
                "handoff_bytes": len(payload),
                "handoff_ms": self._last_handoff_ms,
            },
        })

    async def _disagg_local_remainder(self, d, g, first_res, first,
                                      gen_span, reason: str):
        """Handoff fallback: finish the request on THIS engine (it holds
        or recomputes the prefix) so disaggregation failures degrade to
        unified serving instead of losing the rollout."""
        self._handoff_fallback += 1
        cont = self._gen_request_from(d, g)
        cont.input_ids = [int(t) for t in d["input_ids"]] + [first]
        cont.max_new_tokens = int(g.get("max_new_tokens", 256)) - 1
        cont.min_new_tokens = max(0, int(g.get("min_new_tokens", 0)) - 1)
        cont.priority = 0
        try:
            res2 = await self._submit_and_wait(cont)
        except RuntimeError as e:
            if gen_span is not None:
                gen_span.end(error=str(e))
            return web.json_response(
                {"qid": cont.qid, "error": str(e)}, status=500
            )
        if res2.error is not None:
            if gen_span is not None:
                gen_span.end(error=res2.error)
            return web.json_response(
                {"qid": res2.qid, "error": res2.error}, status=500
            )
        if res2.interrupted:
            self._n_interrupted += 1
        if gen_span is not None:
            gen_span.end(
                n_tokens=1 + len(res2.output_ids),
                disagg="local-fallback", fallback_reason=reason,
            )
        merged = self._gen_response(
            res2, disagg={"fallback": reason},
        )
        merged["output_ids"] = [first] + list(res2.output_ids)
        merged["output_logprobs"] = (
            list(first_res.output_logprobs) + list(res2.output_logprobs)
        )
        merged["version_start"] = first_res.version_start
        merged["latency"] = first_res.latency + res2.latency
        return web.json_response(merged)

    # ------------------------------------------------------------------
    # Tiered KV plane: restore + peer pull + /kv endpoints
    # (docs/serving.md "KV tiering + global prefix index")
    # ------------------------------------------------------------------

    async def _maybe_restore_prefix(
        self, d: Dict, deadline: Optional[rpc.Deadline] = None,
    ) -> Optional[str]:
        """Best-effort prefix restore for a returning session; returns
        the tier it hit ('host'/'disk'/'peer') or None. Never raises —
        every failure path is a plain re-prefill."""
        try:
            return await self._restore_prefix_impl(d, deadline=deadline)
        except Exception:
            logger.warning(
                f"kv restore for {d.get('qid')!r} failed; "
                f"falling back to re-prefill", exc_info=True,
            )
            return None

    async def _restore_prefix_impl(
        self, d: Dict, deadline: Optional[rpc.Deadline] = None,
    ) -> Optional[str]:
        qid = str(d.get("qid") or "")
        input_ids = [int(t) for t in (d.get("input_ids") or [])]
        eng = self.engine
        if (
            not qid
            or len(input_ids) <= self.cfg.kv_page_size
            or eng.has_parked(qid)
        ):
            return None
        kv_source = str(d.get("kv_source") or "")
        if eng.kv_tier is None and (
            not kv_source or kv_source == self.address
        ):
            return None
        # Chaos point: tests arm this to break restores and prove the
        # continuation still completes via re-prefill.
        await faults.maybe_fail_async("gserver.kv_restore")
        loop = asyncio.get_running_loop()
        t0 = time.monotonic()
        span_t0 = tracing.now_ns() if tracing.enabled() else 0
        # 1) Local tier (restore_from_tier blocks on the engine loop
        #    door + device staging: executor, never the event loop).
        if eng.kv_tier is not None:
            n = await loop.run_in_executor(
                None, eng.restore_from_tier, qid, input_ids
            )
            if n:
                self._last_kv_restore_ms = (time.monotonic() - t0) * 1000.0
                if tracing.enabled():
                    tracing.record_span(
                        "server.kv_restore", span_t0,
                        ctx=tracing.extract_from(d), qid=qid,
                        tier="local", n_tokens=n,
                    )
                return "local"
        # 2) Peer pull over /kv/{manifest,chunk} — the weight-plane hop
        #    applied to KV: hash-verified chunks, Range resume.
        if not kv_source or kv_source == self.address:
            return None
        sess = await self._handoff_sess()
        async with sess.get(
            f"{kv_source}/kv/manifest", params={"qid": qid}
        ) as r:
            if r.status != 200:
                self._kv_peer_failed += 1
                return None
            man = await r.json()
        hmeta = man.get("meta") or {}
        toks = [int(t) for t in (hmeta.get("tokens") or [])]
        use = min(len(toks), len(input_ids) - 1)
        if (
            use < self.cfg.kv_page_size
            or toks[:use] != input_ids[:use]
            or int(hmeta.get("version", -1)) != eng.version
        ):
            # Wrong content or stale version: don't pay the transfer.
            return None
        payload = await self._fetch_handoff_payload(
            kv_source, qid, hmeta, path="/kv/chunk", deadline=deadline
        )
        await loop.run_in_executor(
            None, eng.import_kv_handoff, hmeta, payload
        )
        self._kv_peer_hits += 1
        self._kv_peer_bytes += len(payload)
        self._last_kv_restore_ms = (time.monotonic() - t0) * 1000.0
        if tracing.enabled():
            tracing.record_span(
                "server.kv_restore", span_t0,
                ctx=tracing.extract_from(d), qid=qid, tier="peer",
                source=kv_source, n_tokens=len(toks),
                bytes=len(payload),
            )
        return "peer"

    async def _h_kv_manifest(self, request: web.Request) -> web.Response:
        """Peer-pull hop 1: the handoff meta for a prefix this server
        holds (tier entry served as-is; an HBM park is exported into
        the tier first so /kv/chunk can stream its bytes)."""
        from areal_tpu.base.wire_schemas import KV_TIER_V1

        qid = request.query.get("qid", "")
        try:
            # stage_peer_export can block on the engine loop door (HBM
            # export path): executor, never the event loop.
            meta = await asyncio.get_running_loop().run_in_executor(
                None, self.engine.stage_peer_export, qid
            )
        except KeyError as e:
            return web.json_response({"error": str(e)}, status=404)
        except Exception as e:
            return web.json_response({"error": repr(e)}, status=503)
        self._kv_manifests_served += 1
        return web.json_response({
            "schema": KV_TIER_V1, "qid": qid,
            "holder": self.address, "meta": meta,
        })

    @staticmethod
    async def _serve_ranged(
        payload: bytes, request: web.Request
    ) -> web.Response:
        """Range-aware byte serving shared by the handoff blob and the
        tier chunk endpoints. The ``gserver.kv_chunk_bytes`` chaos
        point (corrupt action) fires on the bytes ACTUALLY SERVED —
        the Range slice, like weight_plane.chunk_bytes — so an armed
        corruption is guaranteed to reach the puller's sha256 verify
        instead of possibly flipping bytes outside the requested
        window (a silent no-op drill); async because a delay/hang arm
        must wedge this one request, not the event loop."""
        rng = request.headers.get("Range")
        if rng and rng.startswith("bytes="):
            try:
                a, _, b = rng[len("bytes="):].partition("-")
                start = int(a)
                end = int(b) if b else len(payload) - 1
            except ValueError:
                return web.Response(status=416)
            if start >= len(payload):
                return web.Response(status=416)
            end = min(end, len(payload) - 1)
            body = await faults.maybe_corrupt_async(
                "gserver.kv_chunk_bytes", payload[start: end + 1]
            )
            return web.Response(
                body=body, status=206,
                headers={"Content-Range":
                         f"bytes {start}-{end}/{len(payload)}"},
            )
        body = await faults.maybe_corrupt_async(
            "gserver.kv_chunk_bytes", payload
        )
        return web.Response(body=body)

    async def _h_kv_chunk(self, request: web.Request) -> web.Response:
        """Peer-pull hop 2: serve a held prefix's payload bytes (the
        puller verifies per-chunk hashes — the hash, not this server,
        is the authority)."""
        qid = request.query.get("qid", "")
        # peer_payload may read (and hash-verify) a disk-tier entry:
        # executor, never the event loop.
        got = await asyncio.get_running_loop().run_in_executor(
            None, self.engine.peer_payload, qid
        )
        if got is None:
            return web.json_response(
                {"error": f"no tiered prefix for {qid!r}"}, status=404
            )
        resp = await self._serve_ranged(got[1], request)
        self._kv_chunks_served += 1
        # Bytes actually on the wire (the Range slice), not the whole
        # payload per chunk request — a 10-chunk pull must read as one
        # payload, not ten.
        self._kv_chunk_bytes_served += len(resp.body or b"")
        return resp

    async def _h_kv_index(self, request: web.Request) -> web.Response:
        """Holdings advertisement for the manager's global prefix
        index: HBM parks (loop-refreshed snapshot) + tier entries."""
        from areal_tpu.base.wire_schemas import KV_TIER_V1

        eng = self.engine
        held = eng.parked_index()
        if eng.kv_tier is not None:
            held += await asyncio.get_running_loop().run_in_executor(
                None, eng.kv_tier.held
            )
        return web.json_response({
            "schema": KV_TIER_V1, "url": self.address, "held": held,
        })

    async def _h_kv_handoff(self, request: web.Request) -> web.Response:
        """Decode side: pull the blob from the prefill server (chunked,
        hash-verified, Range-resumable), import it into the engine, and
        run the decode stream as a priority-0 continuation."""
        await faults.maybe_fail_async("gserver.kv_import")
        d = await request.json()
        from areal_tpu.engine.kv_handoff import (
            KVHandoffError, KVHandoffVersionMismatch,
        )

        qid = str(d["qid"])
        meta = d["meta"]
        source = d["source"]
        imp_span = tracing.start_span(
            "server.kv_import", ctx=tracing.extract_from(d),
            qid=qid, source=source,
            n_tokens=int(meta.get("n_tokens", 0)),
        )
        t0 = time.monotonic()
        try:
            payload = await self._fetch_handoff_payload(
                source, qid, meta,
                deadline=rpc.Deadline.from_headers(request.headers),
            )
        except Exception as e:
            if imp_span is not None:
                imp_span.end(error=repr(e))
            return web.json_response(
                {"qid": qid, "error": f"transfer failed: {e!r}"}, status=502
            )
        self._last_kv_transfer_ms = (time.monotonic() - t0) * 1000.0
        try:
            await asyncio.get_running_loop().run_in_executor(
                None, self.engine.import_kv_handoff, meta, payload
            )
        except KVHandoffVersionMismatch as e:
            if imp_span is not None:
                imp_span.end(error=repr(e))
            return web.json_response(
                {"qid": qid, "error": str(e),
                 "version": self.engine.version},
                status=409,
            )
        except (KVHandoffError, RuntimeError, TimeoutError) as e:
            if imp_span is not None:
                imp_span.end(error=repr(e))
            return web.json_response(
                {"qid": qid, "error": str(e)}, status=503
            )
        g = d.get("gconfig", {})
        cont = self._gen_request_from(
            {"qid": qid,
             "input_ids": list(meta["tokens"]) + [int(d["first_token"])],
             "priority": 0},
            g,
        )
        try:
            res = await self._submit_and_wait(cont)
        except RuntimeError as e:
            if imp_span is not None:
                imp_span.end(error=str(e))
            return web.json_response({"qid": qid, "error": str(e)}, status=500)
        if res.error is not None:
            if imp_span is not None:
                imp_span.end(error=res.error)
            return web.json_response(
                {"qid": qid, "error": res.error}, status=500
            )
        if res.interrupted:
            self._n_interrupted += 1
        if imp_span is not None:
            imp_span.end(
                bytes=len(payload),
                transfer_ms=self._last_kv_transfer_ms,
                import_ms=self.engine.last_kv_import_ms,
                n_tokens_out=len(res.output_ids),
            )
        return web.json_response(self._gen_response(
            res,
            transfer_ms=self._last_kv_transfer_ms,
            import_ms=self.engine.last_kv_import_ms,
        ))

    async def _fetch_handoff_payload(
        self, source: str, qid: str, meta: Dict,
        path: str = "/kv_handoff/blob",
        deadline: Optional[rpc.Deadline] = None,
    ) -> bytes:
        """Chunked pull of a KV blob (the disagg export stash, or a
        peer's KV tier via ``path="/kv/chunk"``): per-chunk sha256
        verify, mid-chunk Range resume on torn reads — the weight-plane
        transfer discipline applied to the KV hop. Per-chunk attempts,
        timeouts and backoff come from the unified RPC policy
        (AREAL_RPC_* knobs, base/rpc.py) instead of the old hardcoded
        4-attempt/0.05s loop, and the caller's propagated deadline caps
        every attempt — a rollout with 2s of budget left never waits a
        full blob timeout here.

        Regression note (areal-lint blocking-async): verify_chunk used
        to run inline here — sha256 over a multi-MB KV chunk is ~10ms+
        of CPU per chunk on the 2-core host, paid ON the event loop
        while this decode server is streaming other requests' tokens
        (the PR 7 ITL-stall class). It now runs in the default
        executor, like the weight plane's ChunkStore.fetch."""
        from areal_tpu.base.chunking import chunk_spans, verify_chunk

        index = meta["chunks"]
        total = int(index["total_bytes"])
        buf = bytearray(total)
        sess = await self._handoff_sess()
        policy = rpc.default_policy()
        for i, (off, length) in enumerate(
            chunk_spans(total, int(index["chunk_bytes"]))
        ):
            state = {"got": 0}

            async def attempt(attempt_timeout: float) -> None:
                import aiohttp

                start = off + state["got"]
                dl = (deadline or rpc.Deadline.after(attempt_timeout))
                try:
                    async with sess.get(
                        f"{source}{path}",
                        params={"qid": qid},
                        headers=dl.headers(
                            {"Range": f"bytes={start}-{off + length - 1}"}
                        ),
                        timeout=aiohttp.ClientTimeout(total=attempt_timeout),
                    ) as r:
                        if r.status not in (200, 206):
                            raise OSError(
                                f"blob fetch {r.status}: "
                                f"{(await r.text())[:200]}"
                            )
                        data = await r.read()
                        if r.status == 200:
                            # Range-less server: slice the full payload.
                            data = data[start: off + length]
                except aiohttp.ClientError as e:
                    raise OSError(f"blob fetch failed: {e!r}") from e
                take = min(len(data), length - state["got"])
                buf[start: start + take] = data[:take]
                state["got"] += take
                if state["got"] < length:
                    raise OSError(
                        f"short read {state['got']}/{length}"
                    )  # Range resume continues from the new offset
                ok = await asyncio.get_running_loop().run_in_executor(
                    None, verify_chunk,
                    bytes(buf[off: off + length]), index["hashes"][i],
                )
                if not ok:
                    state["got"] = 0  # corrupt chunk: refetch whole
                    raise ValueError(f"chunk {i} content-hash mismatch")

            try:
                await rpc.retry_async(
                    attempt, policy=policy, deadline=deadline,
                    retryable=rpc.RETRYABLE_DEFAULT,
                    what=f"kv chunk {i} <- {source}{path}",
                )
            except rpc.RpcError as e:
                raise RuntimeError(
                    f"chunk {i} unrecoverable after retries: {e}"
                ) from e
        return bytes(buf)

    async def _h_kv_blob(self, request: web.Request) -> web.Response:
        qid = request.query.get("qid", "")
        ent = self._handoff_store.get(qid)
        if ent is None:
            return web.json_response(
                {"error": f"no handoff blob for {qid!r}"}, status=404
            )
        return await self._serve_ranged(ent[1], request)

    # ------------------------------------------------------------------
    # Drain-then-leave + KV migration (docs/fault_tolerance.md
    # "Fleet elasticity + manager HA")
    # ------------------------------------------------------------------

    async def _h_drain(self, request: web.Request) -> web.Response:
        """Drain-then-leave, server side: quiesce admission NOW (every
        new /generate sheds 429), let in-flight work finish, migrate
        parked prefixes to the given peers over the /kv wire, then
        deregister and exit with a graceful heartbeat-stop marker the
        manager folds into a clean removal. Returns immediately; GET
        /drain reports progress."""
        await faults.maybe_fail_async("gserver.drain")
        d = await request.json()
        if self._draining:
            return web.json_response(
                {"success": True, "already": True, **self._drain_state}
            )
        self._draining = True
        migrate = [
            u for u in (d.get("migrate_to") or [])
            if u and u != self.address
        ]
        self._drain_state.update(
            draining=True, reason=str(d.get("reason") or "")
        )
        hb = getattr(self, "_heartbeat", None)
        if hb is not None:
            # Advertise the drain through the heartbeat (name_resolve
            # file I/O: executor, never the event loop).
            await asyncio.get_running_loop().run_in_executor(
                None, lambda: hb.update_payload(draining=True)
            )
        # Keep a strong reference: the loop holds tasks weakly, and a
        # GC'd drain task would leave the server shedding 429 forever
        # without ever migrating or exiting.
        self._drain_task_handle = asyncio.get_running_loop().create_task(
            self._drain_task(migrate, bool(d.get("exit", True)))
        )
        tracing.event(
            "server.drain", ctx=tracing.extract_from(d),
            n_targets=len(migrate), reason=str(d.get("reason") or ""),
        )
        logger.info(
            f"drain started ({d.get('reason')!r}): migrating KV to "
            f"{len(migrate)} peer(s), {self.engine.n_running} in flight"
        )
        return web.json_response({"success": True, "draining": True})

    async def _h_drain_status(self, request: web.Request) -> web.Response:
        return web.json_response({
            "address": self.address, **self._drain_state,
            "n_running": self.engine.n_running,
            "queue_depth": self.engine.queue_depth,
        })

    async def _drain_task(self, migrate_to, exit_after: bool):
        t0 = time.monotonic()
        loop = asyncio.get_running_loop()
        # Function-scope counters: the failure path below must report
        # honest numbers (whatever was NOT migrated when the task died
        # is lost with the process — never a clean 0/0 departure).
        held: Dict[str, int] = {}
        migrated = lost = stale = 0
        # Lower bound for the failure path: if the authoritative
        # loop-door enumeration below never completes (wedged engine),
        # the snapshot count keeps the loss report honest instead of a
        # clean 0/0 departure. Not used for migration itself — the
        # snapshot can contain already-consumed parks.
        snap_count = len(self.engine.parked_index())
        try:
            # 1) Quiesce: admission already sheds; wait out in-flight
            #    requests (bounded — a wedged slot must not block the
            #    departure forever).
            deadline = t0 + self.cfg.drain_wait_s
            while time.monotonic() < deadline:
                if (
                    self.engine.n_running == 0
                    and self.engine.queue_depth == 0
                ):
                    break
                await asyncio.sleep(0.1)
            # 2) Migrate parked prefixes (HBM parks + tier entries)
            #    over the hash-verified /kv wire: peers pull chunks
            #    from our /kv/chunk and park them in THEIR tier, so
            #    returning sessions restore there instead of paying a
            #    full re-prefill. Version-stale entries are dropped
            #    (unrestorable under the current weights — not a loss).
            # Authoritative loop-door read, NOT the ~0.2s-stale
            # snapshot: a prefix parked moments before the drain must
            # not be silently left behind (parked entries carry the
            # live engine version).
            parked = await loop.run_in_executor(
                None, self.engine.parked_qids_now
            )
            for qid in parked:
                held[qid] = int(self.engine.version)
            if self.engine.kv_tier is not None:
                for e in await loop.run_in_executor(
                    None, self.engine.kv_tier.held
                ):
                    held.setdefault(e["qid"], int(e.get("version", -1)))
            self._drain_state["held"] = len(held)
            sess = (
                await self._handoff_sess() if migrate_to and held else None
            )
            for i, (qid, ver) in enumerate(sorted(held.items())):
                if ver >= 0 and ver != self.engine.version:
                    stale += 1
                    continue
                ok = False
                peer_409 = False
                if sess is not None:
                    try:
                        # stage_peer_export blocks on the engine loop
                        # door for HBM parks: executor.
                        meta = await loop.run_in_executor(
                            None, self.engine.stage_peer_export, qid
                        )
                    except Exception:
                        logger.warning(
                            f"drain: staging {qid!r} failed",
                            exc_info=True,
                        )
                        meta = None
                    # Rotate through EVERY peer starting at this
                    # prefix's round-robin home: one tierless or
                    # blipping peer must not turn its share of the
                    # prefixes into losses the others would accept.
                    k = i % len(migrate_to)
                    targets = migrate_to[k:] + migrate_to[:k]
                    for target in targets if meta is not None else []:
                        try:
                            async with sess.post(
                                f"{target}/kv/accept",
                                json={"qid": qid, "meta": meta,
                                      "source": self.address},
                            ) as r:
                                body = await r.json()
                                ok = r.status == 200 and bool(
                                    body.get("success")
                                )
                                peer_409 = r.status == 409
                        except Exception:
                            logger.warning(
                                f"drain: migrating {qid!r} to "
                                f"{target} failed", exc_info=True,
                            )
                        if ok or peer_409:
                            # 409 = version skew; every peer sits at
                            # the same fleet version — no point asking
                            # the rest.
                            break
                if ok:
                    migrated += 1
                elif peer_409:
                    # The PEER rejected on version skew: the fleet cut
                    # over to a new version while we drained (draining
                    # servers are excluded from fanouts, so OUR engine
                    # version froze and the local check above cannot
                    # see it). The prefix is unrestorable under the
                    # fleet's current weights — stale, not lost.
                    stale += 1
                else:
                    lost += 1
            self._drain_state.update(
                migrated=migrated, lost=lost, stale_dropped=stale,
                drain_ms=(time.monotonic() - t0) * 1000.0, done=True,
            )
            # 3) Deregister the per-index discovery record (the
            #    heartbeat-stop in the worker exit path is the
            #    authoritative departed marker); carry the drain
            #    results on that final record for the manager's log.
            def _dereg():
                try:
                    name_resolve.delete(names.gen_server_url(
                        self.cfg.experiment_name, self.cfg.trial_name,
                        str(self.cfg.server_index),
                    ))
                except Exception:
                    pass

            await loop.run_in_executor(None, _dereg)
            hb = getattr(self, "_heartbeat", None)
            if hb is not None:
                await loop.run_in_executor(
                    None,
                    lambda: hb.update_payload(
                        drain_migrated=migrated, drain_lost=lost
                    ),
                )
            logger.info(
                f"drain complete in "
                f"{self._drain_state['drain_ms']:.0f}ms: migrated "
                f"{migrated}, lost {lost}, stale {stale} of "
                f"{len(held)} held prefix(es)"
            )
        except Exception:
            # Honest accounting: everything held and not yet migrated
            # (or proven stale) dies with this process — report it as
            # lost on the final heartbeat instead of a clean 0/0. The
            # snapshot lower bound covers failures BEFORE the
            # authoritative enumeration populated `held`.
            lost = max(
                lost,
                len(held) - migrated - stale,
                snap_count - migrated - stale,
            )
            self._drain_state.update(
                migrated=migrated, lost=lost, stale_dropped=stale,
                done=True, failed=True,
                drain_ms=(time.monotonic() - t0) * 1000.0,
            )
            hb = getattr(self, "_heartbeat", None)
            if hb is not None:
                try:
                    await loop.run_in_executor(
                        None,
                        lambda: hb.update_payload(
                            drain_migrated=migrated, drain_lost=lost
                        ),
                    )
                except Exception:
                    pass
            logger.exception("drain task failed")
        finally:
            if exit_after:
                # Poll loop exits; Worker.run()'s finally stops the
                # heartbeat with the graceful marker and runs
                # _exit_hook.
                self.exit()

    async def _h_kv_accept(self, request: web.Request) -> web.Response:
        """Drain-migration ingest: pull a departing peer's prefix blob
        over the hash-verified /kv/chunk wire and park it in the LOCAL
        tier (no HBM import — the session may return to any server;
        the entry is advertised via /kv/index, so the manager's global
        prefix index re-routes returning sessions here)."""
        await faults.maybe_fail_async("gserver.kv_accept")
        d = await request.json()
        qid = str(d.get("qid") or "")
        meta = d.get("meta") or {}
        source = str(d.get("source") or "")
        if self.engine.kv_tier is None:
            return web.json_response(
                {"success": False, "error": "no kv tier"}, status=503
            )
        if not qid or not source or not meta:
            return web.json_response(
                {"success": False, "error": "qid/meta/source required"},
                status=400,
            )
        if int(meta.get("version", -1)) != self.engine.version:
            return web.json_response(
                {"success": False,
                 "error": f"version {meta.get('version')} != "
                          f"{self.engine.version}"},
                status=409,
            )
        try:
            payload = await self._fetch_handoff_payload(
                source, qid, meta, path="/kv/chunk",
                deadline=rpc.Deadline.from_headers(request.headers),
            )
        except Exception as e:
            return web.json_response(
                {"success": False, "error": f"transfer failed: {e!r}"},
                status=502,
            )
        await asyncio.get_running_loop().run_in_executor(
            None, self.engine.kv_tier.put, qid, meta, payload
        )
        self._kv_accepted += 1
        self._kv_accept_bytes += len(payload)
        tracing.event(
            "server.kv_accept", ctx=tracing.extract_from(d),
            qid=qid, source=source, bytes=len(payload),
        )
        return web.json_response({"success": True, "bytes": len(payload)})

    async def _h_set_role(self, request: web.Request) -> web.Response:
        """Elastic re-role (manager sizer): flip the live pool role.
        Drain + flip — in-flight requests finish under the old behavior
        (the engine is identical either way); the manager already
        stopped routing the old kind of work here. Weights stay
        resident."""
        d = await request.json()
        role = str(d.get("role", ""))
        if role not in ("unified", "prefill", "decode"):
            return web.json_response(
                {"success": False, "error": f"bad role {role!r}"}, status=400
            )
        with self._role_lock:
            prev, self.role = self.role, role
        tracing.event(
            "server.set_role", ctx=tracing.extract_from(d),
            role=role, previous=prev, n_running=self.engine.n_running,
        )
        logger.info(f"re-roled {prev} -> {role} "
                    f"({self.engine.n_running} in flight)")
        return web.json_response({
            "success": True, "role": role, "previous": prev,
            "n_running": self.engine.n_running,
            "queue_depth": self.engine.queue_depth,
        })

    async def _h_configure(self, request: web.Request) -> web.Response:
        """Runtime admission-watermark overrides (bench A/B arms flip
        backpressure off and back without restarting the fleet), plus —
        ONLY when the AREAL_CHAOS_HTTP knob armed it at boot — runtime
        fault-injection control: ``{"faults": "<AREAL_FAULTS spec>"}``
        arms points in THIS process, ``{"faults_reset": true}`` clears
        them, and the response carries per-point hit counts. The chaos
        campaign (tests/system/test_chaos_campaign.py) sweeps every
        declared fault point against one long-lived subprocess fleet
        through this; a production fleet (knob off) refuses with 403."""
        d = await request.json()
        chaos_keys = (
            "faults" in d or d.get("faults_reset") or "faults_hits" in d
        )
        # Refusals FIRST, before anything mutates: a request the server
        # answers 403/400 must leave zero trace — no half-applied
        # watermarks, no arms left standing behind an error response.
        if chaos_keys:
            from areal_tpu.base import env_registry

            if not env_registry.get_bool("AREAL_CHAOS_HTTP"):
                return web.json_response(
                    {"success": False,
                     "error": "chaos control disabled "
                              "(set AREAL_CHAOS_HTTP=1 at server boot)"},
                    status=403,
                )
            try:
                # Registry-verified: a typo'd point in a remote hits
                # query must 400, not silently report 0 hits — and a
                # typo'd point in an arming spec must 400, not arm a
                # silent no-op behind success:True.
                for p in d.get("faults_hits", []):
                    faults.check_declared(str(p))
                for entry in str(d.get("faults") or "").split(";"):
                    entry = entry.strip()
                    if entry:
                        faults.check_declared(
                            entry.partition("=")[0].partition("@")[0].strip()
                        )
            except ValueError as e:
                return web.json_response(
                    {"success": False, "error": str(e)}, status=400,
                )
        changed = {}
        for key, cast in (("max_queue_depth", int),
                          ("max_queued_tokens", int),
                          ("shed_retry_after_s", float)):
            if key in d:
                val = d[key]
                setattr(self.cfg, key, None if val is None else cast(val))
                changed[key] = val
        resp = {"success": True, "changed": changed}
        if chaos_keys:
            if d.get("faults_reset"):
                faults.reset()
                changed["faults_reset"] = True
            spec = d.get("faults")
            if spec:
                faults.load_env(str(spec))
                changed["faults"] = spec
            resp["faults_armed"] = faults.armed_points()
            resp["faults_hits"] = {
                p: faults.hits_declared(str(p))
                for p in d.get("faults_hits", [])
            }
        return web.json_response(resp)

    async def _h_update_weights(self, request: web.Request) -> web.Response:
        await faults.maybe_fail_async("gserver.update_weights")
        d = await request.json()
        upd_span = tracing.start_span(
            "server.weight_update",
            ctx=tracing.extract_from(d),
            version=d.get("version"),
            n_running=self.engine.n_running,
        )
        model_path = d["model_path"]
        allow_interrupt = bool(d.get("allow_interrupt", True))
        version = d.get("version")
        # is_stale_update takes the engine's stage lock, which an
        # in-flight update_params holds for the whole multi-second
        # staging — run it in the executor like everything else that can
        # block, or every in-flight HTTP response stalls behind it.
        stale = await asyncio.get_running_loop().run_in_executor(
            None,
            self.engine.is_stale_update,
            None if version is None else int(version),
        )
        if stale:
            # Retry of a version that already staged/landed (manager
            # flush timeout): skip the multi-GB reload entirely, but
            # still honor the interrupt escalation — the retry may be
            # asking a drain-blocked staging to stop waiting.
            if allow_interrupt:
                self.engine.escalate_pending_interrupt()
            logger.info(f"skipping stale weight update v{version}")
            if upd_span is not None:
                upd_span.end(stale=True)
            return web.json_response(
                {"success": True, "stale": True,
                 "num_paused_requests": self.engine.n_running}
            )
        try:
            params, info = await asyncio.get_running_loop().run_in_executor(
                None, self._load_params, model_path,
                None if version is None else int(version),
            )
        except Exception as e:
            logger.exception("weight update load failed")
            if upd_span is not None:
                upd_span.end(error=repr(e))
            return web.json_response({"success": False, "error": repr(e)}, status=500)
        self._last_load_info = info
        n_running = self.engine.n_running
        # update_params stages the full host->device transfer on the
        # calling thread — keep it off the event loop like the load, or
        # every in-flight HTTP response stalls behind it.
        await asyncio.get_running_loop().run_in_executor(
            None,
            lambda: self.engine.update_params(
                params,
                allow_interrupt=allow_interrupt,
                version=None if version is None else int(version),
            ),
        )
        logger.info(
            f"weight update: source={info['source']} "
            f"load={info['load_s']:.3f}s dump_version={info['version']}"
        )
        if upd_span is not None:
            upd_span.end(
                source=info["source"], load_s=info["load_s"],
                n_paused=n_running,
            )
        return web.json_response(
            {
                "success": True,
                "num_paused_requests": n_running,
                "load_s": info["load_s"],
                "source": info["source"],
            }
        )

    def _load_params(self, model_path: str, want_version=None):
        """Fastest source first: tmpfs raw -> disk raw -> pickle -> HF
        (system/weight_transfer.load_for_serving). With a pinned
        want_version, a dump that doesn't hold exactly that version
        raises WeightVersionMismatch after brief retries — the manager
        pins the engine to its published version, so silently loading a
        stale raw dump (or a version:-1 pickle/HF fallback) would serve
        old weights under a new version label."""
        from areal_tpu.system.weight_transfer import (
            load_for_serving, shm_transfer_dir,
        )

        # The realloc dump dir is .../param_realloc/<role>; the tmpfs
        # fast-path dump (model_worker._param_realloc) is keyed by the
        # same role name.
        role = os.path.basename(model_path.rstrip("/"))
        shm = shm_transfer_dir(
            self.cfg.experiment_name, self.cfg.trial_name, role
        )
        return load_for_serving(
            model_path, shm_dir=shm, want_version=want_version
        )

    # ------------------------------------------------------------------
    # Weight-distribution plane (system/weight_plane.py)
    # ------------------------------------------------------------------

    async def _h_distribute_weights(self, request: web.Request) -> web.Response:
        """Prefetch version-N chunks into host memory while version N-1
        keeps serving. Returns when the payload is complete+verified, so
        the manager can use this server as a parent in the next wave."""
        await faults.maybe_fail_async("gserver.distribute_weights")
        d = await request.json()
        version = int(d["version"])
        upstreams = [u for u in (d.get("upstreams") or []) if u]
        origin = d.get("origin")
        # A sharded server accepts exactly ITS shard's stream: fetching
        # another rank's slice would waste a full shard of ingress and
        # the cutover below could never place it.
        man_shard = (d.get("manifest") or {}).get("shard") or {}
        man_key = (
            int(man_shard.get("tp_rank") or 0),
            int(man_shard.get("tp_degree") or 1),
        )
        want_key = getattr(self, "_weight_shard", None) or (0, 1)
        if man_key != want_key:
            # Teach the caller our real spec: a manager whose shard map
            # hasn't caught up yet (fanout racing the first heartbeat)
            # corrects itself from this instead of evicting us.
            return web.json_response(
                {"success": False,
                 "error": f"manifest shard {man_key} != server shard "
                          f"{want_key}",
                 "weight_shard": list(want_key)},
                status=409,
            )
        fetch_span = tracing.start_span(
            "server.weight_fetch",
            ctx=tracing.extract_from(d),
            version=version, n_upstreams=len(upstreams),
        )
        with self._wp_lock:
            held = self._wp_store
            joining = False
            if held is not None and held.version > version:
                # A stale edge (manager retry from an older fanout):
                # reject before paying the model-sized staging
                # allocation below.
                if fetch_span is not None:
                    fetch_span.end(error="superseded")
                return web.json_response(
                    {"success": False,
                     "error": f"superseded by v{held.version}"},
                    status=409,
                )
            if held is not None and held.version == version:
                if self._wp_state == "ready":
                    # Manager retry / duplicate edge: already holding it.
                    if fetch_span is not None:
                        fetch_span.end(already_held=True)
                    return web.json_response(
                        {"success": True, "already_held": True,
                         "transfer_ms": self._wp_transfer_ms,
                         "verify_ms": self._wp_verify_ms}
                    )
                if self._wp_state == "fetching":
                    # A duplicate for an IN-FLIGHT fetch (manager retry
                    # after a wave timeout) joins it instead of
                    # replacing the store: restarting from byte 0 would
                    # discard every verified chunk, and a transfer
                    # slower than the manager's timeout could then
                    # never complete at all.
                    store, joining = held, True
        if not joining:
            # The store's host-memory staging buffer is model-sized and
            # zero-filled at construction: allocate on an executor
            # thread so the event loop keeps streaming in-flight
            # /generate responses (the whole point of the overlap).
            try:
                store = await asyncio.get_running_loop().run_in_executor(
                    None, ChunkStore, d["manifest"]
                )
            except Exception as e:
                if fetch_span is not None:
                    fetch_span.end(error=repr(e))
                return web.json_response(
                    {"success": False, "error": repr(e)}, status=400
                )
            with self._wp_lock:
                held = self._wp_store
                if held is not None and held.version > version:
                    # A newer version landed while we allocated; this
                    # edge is stale — publishing ours would roll the
                    # holder back.
                    if fetch_span is not None:
                        fetch_span.end(error="superseded")
                    return web.json_response(
                        {"success": False,
                         "error": f"superseded by v{held.version}"},
                        status=409,
                    )
                if held is not None and held.version == version:
                    if self._wp_state == "ready":
                        if fetch_span is not None:
                            fetch_span.end(already_held=True)
                        return web.json_response(
                            {"success": True, "already_held": True,
                             "transfer_ms": self._wp_transfer_ms,
                             "verify_ms": self._wp_verify_ms}
                        )
                    if self._wp_state == "fetching":
                        # A concurrent duplicate won the publish while
                        # we allocated: join its in-flight fetch.
                        store, joining = held, True
                if not joining:
                    self._wp_store = store
                    self._wp_state = "fetching"

        if joining:
            deadline = time.monotonic() + float(d.get("deadline_s") or 600.0)

            def _await_inflight():
                while time.monotonic() < deadline:
                    with self._wp_lock:
                        if self._wp_store is not store:
                            return "superseded"
                        if self._wp_state != "fetching":
                            return self._wp_state
                    time.sleep(0.05)
                return "timeout"

            state = await asyncio.get_running_loop().run_in_executor(
                None, _await_inflight
            )
            with self._wp_lock:
                body = {"success": state == "ready", "joined": True,
                        "transfer_ms": self._wp_transfer_ms,
                        "verify_ms": self._wp_verify_ms}
            if state != "ready":
                body["error"] = f"in-flight fetch ended: {state}"
            if fetch_span is not None:
                fetch_span.end(joined=True, state=state)
            return web.json_response(
                body, status=200 if state == "ready" else 500
            )

        def _fetch():
            faults.maybe_fail("gserver.weight_fetch")
            return store.fetch(
                upstreams,
                origin=origin,
                timeout=float(d.get("chunk_timeout") or 30.0),
                deadline_s=float(d.get("deadline_s") or 600.0),
            )

        try:
            stats = await asyncio.get_running_loop().run_in_executor(None, _fetch)
        except Exception as e:
            with self._wp_lock:
                if self._wp_store is store:
                    self._wp_state = "failed"
            logger.exception("weight-plane prefetch failed")
            if fetch_span is not None:
                fetch_span.end(error=repr(e))
            return web.json_response(
                {"success": False, "error": repr(e)}, status=500
            )
        with self._wp_lock:
            # Both the state flip AND the telemetry are guarded: a fetch
            # superseded by a newer /distribute_weights must not clobber
            # the live version's transfer numbers on /metrics.
            if self._wp_store is store:
                self._wp_state = "ready"
                self._wp_transfer_ms = stats["fetch_s"] * 1000.0
                self._wp_verify_ms = stats["verify_s"] * 1000.0
                self._wp_bytes_from_origin = stats["bytes_from_origin"]
                self._wp_bytes_from_peers = stats["bytes_from_peers"]
                self._wp_expected_bytes = stats["expected_bytes"]
                self._wp_ingress_eq = stats["ingress_payload_equivalents"]
                self._wp_wire = stats.get("wire") or "raw"
        logger.info(
            f"weight-plane prefetch v{version}: "
            f"{stats['total_bytes']} bytes in {stats['fetch_s']:.3f}s "
            f"(origin {stats['bytes_from_origin']}, "
            f"peers {stats['bytes_from_peers']}); still serving "
            f"v{self.engine.version}"
        )
        if fetch_span is not None:
            fetch_span.end(
                fetch_s=stats["fetch_s"], verify_s=stats["verify_s"],
                bytes_from_origin=stats["bytes_from_origin"],
                bytes_from_peers=stats["bytes_from_peers"],
            )
        return web.json_response(
            {"success": True,
             "transfer_ms": self._wp_transfer_ms,
             "verify_ms": self._wp_verify_ms,
             "bytes_from_origin": stats["bytes_from_origin"],
             "bytes_from_peers": stats["bytes_from_peers"],
             "n_chunks": stats["n_chunks"],
             "resumed_chunks": stats["resumed_chunks"]}
        )

    async def _h_cutover_weights(self, request: web.Request) -> web.Response:
        """Swap to the prefetched version: interrupt in-flight requests
        (partial results return for client re-prefill), device-put the
        host buffer, flip. Measured end-to-end, separately from the
        transfer, and compared against the cutover budget."""
        await faults.maybe_fail_async("gserver.cutover_weights")
        d = await request.json()
        version = int(d["version"])
        budget_s = float(d.get("budget_s") or 0.0)
        cut_span = tracing.start_span(
            "server.weight_cutover",
            ctx=tracing.extract_from(d),
            version=version, n_running=self.engine.n_running,
        )
        with self._wp_lock:
            store = self._wp_store
            if (
                store is None or store.version != version
                or self._wp_state != "ready"
            ):
                if cut_span is not None:
                    cut_span.end(error="not holding")
                return web.json_response(
                    {"success": False,
                     "error": f"not holding v{version} "
                              f"(state={self._wp_state})"},
                    status=409,
                )
        n_running = self.engine.n_running

        def _cut():
            shard = store.manifest.get("shard") or {}
            degree = int(shard.get("tp_degree") or 1)
            if degree > 1:
                # Sliced manifest: the leaves ARE this rank's local
                # shards — device_put them straight under the engine's
                # NamedSharding (make_array path), no model-sized host
                # assembly. Requires the engine's addressable mesh slice
                # to be exactly this rank (multi-host TP); anything else
                # fails loudly and the manager evicts/re-syncs.
                from areal_tpu.engine.weight_client import assemble_leaves

                rank = int(shard.get("tp_rank") or 0)
                leaves = assemble_leaves(store)
                gshapes = {
                    e["path"]: tuple(e["global_shape"])
                    for e in store.manifest["leaves"]
                    if "global_shape" in e
                }
                return self.engine.cutover_shard_leaves(
                    {rank: leaves}, degree, version=store.version,
                    allow_interrupt=bool(d.get("allow_interrupt", True)),
                    timeout_s=max(120.0, budget_s * 10.0),
                    global_shapes=gshapes,
                )
            params, v = assemble_params(store)
            return self.engine.cutover_params(
                params, version=v,
                allow_interrupt=bool(d.get("allow_interrupt", True)),
                timeout_s=max(120.0, budget_s * 10.0),
            )

        try:
            cut_s = await asyncio.get_running_loop().run_in_executor(None, _cut)
        except Exception as e:
            logger.exception("weight-plane cutover failed")
            if cut_span is not None:
                cut_span.end(error=repr(e))
            return web.json_response(
                {"success": False, "error": repr(e)}, status=500
            )
        with self._wp_lock:
            self._wp_cutover_ms = cut_s * 1000.0
        self._last_load_info = {
            "source": "weight_plane", "version": version,
            "load_s": self._wp_transfer_ms / 1000.0,
        }
        within = budget_s <= 0.0 or cut_s <= budget_s
        if not within:
            logger.warning(
                f"weight cutover v{version} took {cut_s:.3f}s, over the "
                f"{budget_s:.3f}s budget"
            )
        logger.info(
            f"weight-plane cutover to v{version}: {cut_s * 1000:.1f}ms "
            f"({n_running} request(s) interrupted)"
        )
        if cut_span is not None:
            cut_span.end(
                cutover_s=cut_s, within_budget=within, n_paused=n_running
            )
        return web.json_response(
            {"success": True,
             "cutover_ms": cut_s * 1000.0,
             "transfer_ms": self._wp_transfer_ms,
             "within_budget": within,
             "num_paused_requests": n_running}
        )

    async def _h_weights_manifest(self, request: web.Request) -> web.Response:
        with self._wp_lock:
            store = self._wp_store
        return serve_store_manifest(store, request)

    async def _h_weights_chunk(self, request: web.Request) -> web.Response:
        """Peer hop: serve a verified chunk to a sibling. Valid during
        an in-flight prefetch too (ChunkStore marks chunks servable the
        moment they verify), so deeper tree levels can pipeline."""
        await faults.maybe_fail_async("weight_plane.serve_chunk")
        with self._wp_lock:
            store = self._wp_store
        # The chunk copy (up to weight_chunk_bytes) goes off the event
        # loop: this loop also serves /generate, and a fanout wave means
        # one request per chunk per child — blocking it would defeat the
        # transfer-overlaps-serving design.
        resp, served = await asyncio.get_running_loop().run_in_executor(
            None, serve_store_chunk, store, request
        )
        if served:
            with self._wp_lock:
                self._wp_chunks_served += 1
                self._wp_bytes_served += served
        return resp

    async def _h_metrics(self, request: web.Request) -> web.Response:
        from areal_tpu.base.latency import encode_counts

        m = self.engine.metrics()
        snap = self.engine.latency_snapshot()
        rpc_snap = rpc.stats.snapshot()
        lines = [
            f"areal:num_running_reqs {m['num_running_reqs']}",
            f"areal:num_used_tokens {m['num_used_tokens']}",
            f"areal:total_generated_tokens {m['total_generated']}",
            f"areal:queue_depth {m['queue_depth']}",
            f"areal:queued_prompt_tokens {m['queued_prompt_tokens']}",
            # Admission control: requests shed with 429 (deliberate
            # load-shedding, NOT failures — the manager must never count
            # these toward eviction).
            f"areal:load_shed_total {float(self._n_shed)}",
            # Per-request latency SLOs from the engine loop. Percentiles
            # for humans; raw bucket counts (base/latency.py edges,
            # sparse i:count) for the manager's ratio-of-sums fleet
            # aggregation — percentiles cannot be averaged.
            f"areal:ttft_p50_ms {snap['ttft_p50_ms']}",
            f"areal:ttft_p99_ms {snap['ttft_p99_ms']}",
            f"areal:itl_p50_ms {snap['itl_p50_ms']}",
            f"areal:itl_p99_ms {snap['itl_p99_ms']}",
            f"areal:ttft_hist {encode_counts(snap['ttft_counts']) or '-'}",
            f"areal:itl_hist {encode_counts(snap['itl_counts']) or '-'}",
            f"areal:num_interrupted_reqs {float(self._n_interrupted)}",
            f"areal:weight_version {float(self.engine.version)}",
            f"areal:kv_pages_free {m['kv_pages_free']}",
            f"areal:kv_pages_total {m['kv_pages_total']}",
            # Decode-time MoE router telemetry (zeros for dense models):
            # last-block layer-mean realized drop rate and router
            # entropy, from the packed decode-block columns.
            f"areal:moe_drop_rate {m.get('moe_drop_rate', 0.0)}",
            f"areal:moe_router_entropy {m.get('moe_router_entropy', 0.0)}",
            # Disaggregated serving: live pool role (string surface, like
            # the histogram lines), elastic eligibility (configured role
            # is the re-role pool), and the KV-handoff counters.
            f"areal:role {self.role}",
            f"areal:model_id {self.cfg.model_id or '-'}",
            f"areal:elastic {1.0 if self.cfg.role == 'unified' else 0.0}",
            f"areal:kv_export_total {m['kv_export_total']}",
            f"areal:kv_export_bytes {m['kv_export_bytes']}",
            f"areal:last_kv_export_ms {m['last_kv_export_ms']}",
            f"areal:kv_import_total {m['kv_import_total']}",
            f"areal:kv_import_bytes {m['kv_import_bytes']}",
            f"areal:last_kv_import_ms {m['last_kv_import_ms']}",
            f"areal:last_kv_transfer_ms {self._last_kv_transfer_ms}",
            f"areal:kv_handoff_ok {float(self._handoff_ok)}",
            f"areal:kv_handoff_failed {float(self._handoff_failed)}",
            f"areal:kv_handoff_fallback {float(self._handoff_fallback)}",
            # Tiered KV plane: spill/restore counters + per-tier
            # hit/miss/bytes (docs/serving.md). kv_prefix_lost_total is
            # the residual TRUE-loss count the tier exists to zero out
            # (chaos bench asserts 0 under pressure).
            f"areal:kv_spill_total {m['kv_spill_total']}",
            f"areal:kv_spill_bytes {m['kv_spill_bytes']}",
            f"areal:kv_spill_tokens {m['kv_spill_tokens']}",
            f"areal:kv_restore_total {m['kv_restore_total']}",
            f"areal:kv_restore_host {m['kv_restore_host']}",
            f"areal:kv_restore_disk {m['kv_restore_disk']}",
            f"areal:kv_restore_tokens {m['kv_restore_tokens']}",
            f"areal:kv_prefix_lost_total {m['kv_prefix_lost_total']}",
            f"areal:kv_tier_host_bytes {m.get('kv_tier_host_bytes', 0.0)}",
            f"areal:kv_tier_disk_bytes {m.get('kv_tier_disk_bytes', 0.0)}",
            f"areal:kv_tier_host_entries "
            f"{m.get('kv_tier_host_entries', 0.0)}",
            f"areal:kv_tier_disk_entries "
            f"{m.get('kv_tier_disk_entries', 0.0)}",
            f"areal:kv_tier_misses {m.get('kv_tier_misses', 0.0)}",
            f"areal:kv_tier_corrupt_dropped "
            f"{m.get('kv_tier_dropped_corrupt', 0.0)}",
            f"areal:kv_tier_peer_hits {float(self._kv_peer_hits)}",
            f"areal:kv_tier_peer_bytes {float(self._kv_peer_bytes)}",
            f"areal:kv_tier_peer_failed {float(self._kv_peer_failed)}",
            # Elastic fleet: drain state + KV migration counters
            # (docs/fault_tolerance.md). kv_drain_lost is the drain
            # analogue of kv_prefix_lost_total — the e2e pins it to 0.
            f"areal:draining {1.0 if self._draining else 0.0}",
            f"areal:kv_migrated_out "
            f"{float(self._drain_state.get('migrated', 0))}",
            f"areal:kv_drain_lost {float(self._drain_state.get('lost', 0))}",
            f"areal:kv_accepted {float(self._kv_accepted)}",
            f"areal:kv_accept_bytes {float(self._kv_accept_bytes)}",
            f"areal:last_kv_restore_ms {self._last_kv_restore_ms}",
            f"areal:kv_manifests_served {float(self._kv_manifests_served)}",
            f"areal:kv_chunks_served {float(self._kv_chunks_served)}",
            f"areal:num_preempted_reqs {m['num_preempted_reqs']}",
            f"areal:prefix_cache_hits {m['prefix_cache_hits']}",
            f"areal:prefix_tokens_reused {m['prefix_tokens_reused']}",
            f"areal:prefix_cached_tokens {m['prefix_cached_tokens']}",
            # Fleet hit-rate denominator (manager aggregates ratio of
            # sums across servers, not an average of per-server rates).
            f"areal:total_requests {m['total_requests']}",
            f"areal:spec_tokens_per_step {m['spec_tokens_per_step']}",
            # Raw sums behind the ratio, so the manager can aggregate the
            # fleet yield as sum(emitted)/sum(steps) instead of averaging
            # per-server ratios.
            f"areal:spec_emitted_tokens {m['spec_emitted_tokens']}",
            f"areal:spec_active_steps {m['spec_active_steps']}",
            # RPC substrate counters (base/rpc.py process-global stats):
            # this server's OWN outbound calls — KV/weight chunk pulls,
            # handoff hops — under the unified retry/hedge/breaker
            # discipline (docs/fault_tolerance.md).
            f"areal:rpc_attempts {float(rpc_snap['attempts'])}",
            f"areal:rpc_retries {float(rpc_snap['retries'])}",
            f"areal:rpc_failures {float(rpc_snap['failures'])}",
            f"areal:rpc_hedges {float(rpc_snap['hedges'])}",
            f"areal:rpc_hedge_wins {float(rpc_snap['hedge_wins'])}",
            f"areal:rpc_hedge_cancelled {float(rpc_snap['hedge_cancelled'])}",
            f"areal:rpc_hedge_failures {float(rpc_snap['hedge_failures'])}",
            f"areal:rpc_deadline_expired {float(rpc_snap['deadline_expired'])}",
            f"areal:rpc_breaker_rejections "
            f"{float(rpc_snap['breaker_rejections'])}",
            f"areal:rpc_breaker_opens {float(rpc_snap['breaker_opens'])}",
            f"areal:last_weight_swap_s {m['last_weight_swap_s']}",
            f"areal:last_weight_stage_s {m['last_weight_stage_s']}",
            f"areal:last_weight_load_s "
            f"{self._last_load_info['load_s'] if self._last_load_info else 0.0}",
            f"areal:weight_load_fast_path "
            f"{1.0 if (self._last_load_info or {}).get('source') == 'shm_raw' else 0.0}",
            # Weight-distribution plane: network transfer vs cutover are
            # separate numbers by design — transfer overlaps serving,
            # cutover is the short interrupt+swap window the budget
            # knob bounds.
            f"areal:weight_transfer_ms {self._wp_transfer_ms}",
            f"areal:weight_cutover_ms {self._wp_cutover_ms}",
            f"areal:weight_verify_ms {self._wp_verify_ms}",
            f"areal:weight_bytes_from_origin {float(self._wp_bytes_from_origin)}",
            f"areal:weight_bytes_from_peers {float(self._wp_bytes_from_peers)}",
            f"areal:weight_chunks_served {float(self._wp_chunks_served)}",
            f"areal:weight_bytes_served {float(self._wp_bytes_served)}",
            # Shard-aware expectations: expected_bytes is THIS server's
            # chunk stream size (shard slice and/or quantized wire), so
            # ingress/expected reads 1.0 for a complete sliced fetch —
            # never "incomplete" against the full payload.
            f"areal:weight_expected_bytes {float(self._wp_expected_bytes)}",
            f"areal:weight_ingress_payload_equivalents {self._wp_ingress_eq}",
            f"areal:weight_wire {self._wp_wire}",
            "areal:weight_shard "
            + (
                f"{self._weight_shard[0]}/{self._weight_shard[1]}"
                if self._weight_shard else "-"
            ),
        ]
        return web.Response(text="\n".join(lines) + "\n")

    async def _h_health(self, request: web.Request) -> web.Response:
        return web.json_response(
            {"status": "ok", "version": self.engine.version,
             "role": self.role}
        )

    # ------------------------------------------------------------------

    def _poll(self) -> Optional[PollResult]:
        # Exit when the experiment completes (reference
        # generation_server.py:209-222 watches experiment status).
        try:
            status = name_resolve.get(
                names.experiment_status(
                    self.cfg.experiment_name, self.cfg.trial_name
                )
            )
            if status in ("COMPLETE", "ABORT"):
                return None
        except name_resolve.NameEntryNotFoundError:
            pass
        time.sleep(0.2)
        return PollResult(batch_count=0)

    def _exit_hook(self):
        try:
            self.engine.stop()
            if self._handoff_session is not None:
                asyncio.run_coroutine_threadsafe(
                    self._handoff_session.close(), self._http_loop
                ).result(timeout=5)
            self._http_loop.call_soon_threadsafe(self._http_loop.stop)
            self._http_thread.join(timeout=5)
        except Exception:
            pass

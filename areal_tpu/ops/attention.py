"""Packed variable-length causal attention with GQA.

Replaces the reference's flash-attn varlen path
(realhf/impl/model/modules/attn.py:272-289) the TPU way: batches are packed
token streams with *segment ids* (0 = padding, sequences numbered from 1)
and per-token positions; attention is masked to (same segment) AND
(causal by position). Two implementations share one signature:

- `reference_packed_attention`: dense jnp einsum + mask. O(T^2) memory;
  used on CPU tests and as the numerical oracle.
- `flash_packed_attention` (areal_tpu.ops.pallas.flash_attn): blocked
  Pallas kernel, online softmax, segment-aware block skipping.

`packed_attention` dispatches on platform/size.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from areal_tpu.base import env_registry

NEG_INF = -2.0**30
LANES = 128  # TPU lane width; splash blocks must be lane-aligned

# checkpoint_name tag for splash-attention residuals (see _splash_kernel).
SPLASH_RESIDUAL_NAME = "splash_attn_residuals"


def segment_causal_mask(
    q_seg: jnp.ndarray, kv_seg: jnp.ndarray, q_pos: jnp.ndarray, kv_pos: jnp.ndarray
) -> jnp.ndarray:
    """Boolean [Tq, Tk]: token i may attend to token j."""
    same = q_seg[:, None] == kv_seg[None, :]
    causal = q_pos[:, None] >= kv_pos[None, :]
    valid = (q_seg[:, None] > 0) & (kv_seg[None, :] > 0)
    return same & causal & valid


def reference_packed_attention(
    q: jnp.ndarray,  # [T, Hq, hd]
    k: jnp.ndarray,  # [T, Hkv, hd]
    v: jnp.ndarray,  # [T, Hkv, hd]
    segment_ids: jnp.ndarray,  # [T] int32, 0 = pad
    positions: jnp.ndarray,  # [T] int32 within-sequence positions
    softmax_scale: Optional[float] = None,
) -> jnp.ndarray:
    T, Hq, hd = q.shape
    Hkv = k.shape[1]
    group = Hq // Hkv
    scale = softmax_scale if softmax_scale is not None else hd**-0.5
    qg = q.reshape(T, Hkv, group, hd).astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    # scores: [Hkv, group, Tq, Tk]
    scores = jnp.einsum("qhgd,khd->hgqk", qg, kf) * scale
    mask = segment_causal_mask(segment_ids, segment_ids, positions, positions)
    scores = jnp.where(mask[None, None], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    # Fully-masked (padding) rows: zero out.
    probs = jnp.where(mask.any(axis=-1)[None, None, :, None], probs, 0.0)
    out = jnp.einsum("hgqk,khd->qhgd", probs, vf)
    return out.reshape(T, Hq, hd).astype(q.dtype)


def decode_attention(
    q: jnp.ndarray,  # [B, Hq, hd] — one new token per sequence
    k_cache: jnp.ndarray,  # [B, S, Hkv, hd]
    v_cache: jnp.ndarray,  # [B, S, Hkv, hd]
    cache_lens: jnp.ndarray,  # [B] valid lengths INCLUDING the new token
    softmax_scale: Optional[float] = None,
) -> jnp.ndarray:
    """Single-step decode attention against a padded KV cache."""
    B, Hq, hd = q.shape
    S, Hkv = k_cache.shape[1], k_cache.shape[2]
    group = Hq // Hkv
    scale = softmax_scale if softmax_scale is not None else hd**-0.5
    qg = q.reshape(B, Hkv, group, hd).astype(jnp.float32)
    scores = jnp.einsum("bhgd,bshd->bhgs", qg, k_cache.astype(jnp.float32)) * scale
    pos = jnp.arange(S)[None, :]  # [1, S]
    mask = pos < cache_lens[:, None]  # [B, S]
    scores = jnp.where(mask[:, None, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhgs,bshd->bhgd", probs, v_cache.astype(jnp.float32))
    return out.reshape(B, Hq, hd).astype(q.dtype)


_SPLASH_MASK_CACHE = {}

# AREAL_SPLASH_* snapshot: (bq, bkv, bkvc) targets once taken.
_SPLASH_SNAP = None


def snapshot_splash_blocks():
    """Parse + validate the AREAL_SPLASH_BQ/BKV/BKVC block-size targets
    and pin them for subsequent traces. Called at engine construction so
    a mid-run retrace can't silently mix tuning settings and a bad value
    fails at init instead of inside a jit trace; sweeps re-pin by
    constructing a fresh engine per setting (scripts/mfu_sweep.py)."""
    global _SPLASH_SNAP

    def check(name, v):
        # Defaults live in the env registry, not here (the per-call-site
        # default drift this registry exists to end); knob names stay
        # literal at each get_int so the env-knob checker can see them.
        if v < LANES:
            raise ValueError(f"{name}={v}: splash block targets must be "
                             f">= {LANES}")
        return v

    _SPLASH_SNAP = (
        check("AREAL_SPLASH_BQ", env_registry.get_int("AREAL_SPLASH_BQ")),
        check("AREAL_SPLASH_BKV", env_registry.get_int("AREAL_SPLASH_BKV")),
        check("AREAL_SPLASH_BKVC",
              env_registry.get_int("AREAL_SPLASH_BKVC")),
    )
    return _SPLASH_SNAP


def _splash_block_targets():
    if _SPLASH_SNAP is None:
        # Direct ops use without an engine: snapshot lazily on first use.
        return snapshot_splash_blocks()
    return _SPLASH_SNAP


def _largest_block(n: int, cap: int) -> int:
    """Largest multiple of 128 that divides n and is <= cap (splash
    requires lane-aligned blocks that divide the sequence length)."""
    if n % LANES:
        raise ValueError(
            f"splash attention needs seq len a multiple of {LANES}, got {n}"
        )
    d = (min(cap, n) // LANES) * LANES
    while n % d:
        d -= LANES
    return d


def _splash_kernel(t: int, group: int, interpret: bool = False):
    """Build a tuned splash-attention kernel for seq len `t` (the mask
    object is cached; the kernel itself is rebuilt per trace).

    jax's splash attention (jax.experimental.pallas.ops.tpu.splash_attention,
    the production TPU flash kernel — same role as the flash-attn package
    the reference installs, realhf Dockerfile) is used as an MQA problem
    per kv head: q carries the GQA group as its head axis. Global causal
    mask + segment ids equals our (same segment) & (position causal) mask
    because packed segments are contiguous with ascending positions.
    Block sizes were tuned on v5e (fused bwd, 512/1024 tiles).
    """
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sk,
        splash_attention_mask as sm,
    )

    # Only the mask object is cached: the built kernel holds per-trace
    # mask-info buffers, and reusing it across jit traces leaks tracers
    # (UnexpectedTracerError). Rebuilding per trace is cheap — tracing
    # happens once per compiled program, not per step.
    key = (t, group)
    mask = _SPLASH_MASK_CACHE.get(key)
    if mask is None:
        mask = sm.MultiHeadMask([sm.CausalMask((t, t)) for _ in range(group)])
        _SPLASH_MASK_CACHE[key] = mask

    # Block sizes must divide the sequence length (packed rows are
    # padded to multiples of 128, so t is often e.g. 640 or 1536).
    # Targets are overridable for on-chip tuning (scripts/mfu_sweep.py),
    # validated + pinned at engine construction (snapshot_splash_blocks)
    # so a mid-run retrace cannot mix settings; sweeps re-pin by
    # constructing a fresh engine per setting.
    tq, tkv, tkvc = _splash_block_targets()
    bq = _largest_block(t, tq)
    bkv = _largest_block(t, tkv)
    bkvc = _largest_block(bkv, tkvc)
    bs = sk.BlockSizes(
        block_q=bq, block_kv=bkv, block_kv_compute=bkvc,
        block_q_dkv=bq, block_kv_dkv=bkv, block_kv_dkv_compute=bkvc,
        use_fused_bwd_kernel=True,
    )
    # Residuals are checkpoint-named so the "save_attn" remat policy
    # (models/transformer.py) can pin them: backward then runs the
    # flash bwd kernel without re-running the fwd kernel.
    return sk.make_splash_mqa_single_device(
        mask=mask, block_sizes=bs,
        residual_checkpoint_name=SPLASH_RESIDUAL_NAME,
        interpret=interpret,
    )


def splash_packed_attention(
    q: jnp.ndarray,  # [T, Hq, hd]
    k: jnp.ndarray,  # [T, Hkv, hd]
    v: jnp.ndarray,  # [T, Hkv, hd]
    segment_ids: jnp.ndarray,  # [T] int32, 0 = pad
    positions: jnp.ndarray,  # [T] int32 (unused: causality via stream order)
    softmax_scale: Optional[float] = None,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Packed GQA attention on jax's splash kernel (one MQA call per kv
    head, GQA group as the q-head axis). Pad tokens (segment 0) attend
    only among themselves, so outputs there are finite garbage — masked
    by downstream losses exactly like the other impls."""
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sk,
    )

    t, hq, hd = q.shape
    hkv = k.shape[1]
    group = hq // hkv
    scale = float(softmax_scale) if softmax_scale is not None else hd ** -0.5
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")
    kernel = _splash_kernel(t, group, interpret=bool(interpret))

    # [T, Hq, hd] -> [Hkv, group, T, hd]; k/v -> [Hkv, T, hd]
    qh = (q * jnp.asarray(scale, q.dtype)).transpose(1, 0, 2).reshape(
        hkv, group, t, hd
    )
    kh = k.transpose(1, 0, 2)
    vh = v.transpose(1, 0, 2)
    ids = sk.SegmentIds(q=segment_ids, kv=segment_ids)
    out = jax.vmap(lambda qq, kk, vv: kernel(qq, kk, vv, ids))(qh, kh, vh)
    # [Hkv, group, T, hd] -> [T, Hq, hd]
    return out.reshape(hq, t, hd).transpose(1, 0, 2).astype(q.dtype)


def sharded_splash_attention(
    q: jnp.ndarray,  # [R, T, Hq, hd]
    k: jnp.ndarray,  # [R, T, Hkv, hd]
    v: jnp.ndarray,  # [R, T, Hkv, hd]
    segment_ids: jnp.ndarray,  # [R, T]
    positions: jnp.ndarray,  # [R, T]
    mesh,
    softmax_scale: Optional[float] = None,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """splash attention under `shard_map` for GSPMD programs.

    pallas_call is opaque to the SPMD partitioner — inside a sharded jit
    it would replicate or fail (reference's analogue runs flash-attn under
    megatron TP, realhf/impl/model/modules/attn.py:272-289). Here the
    kernel runs per shard with an explicit layout:

    - rows on (data, fsdp) — fully data-parallel,
    - q heads on `tensor` (column-parallel qkv makes them local already),
      kv heads likewise (requires tensor | Hkv),
    - sequence gathered: in_specs leave T unsharded, so jit all-gathers
      seq-sharded activations into each shard before the kernel — the
      same collective GSPMD inserts for the einsum path's [T, T] scores.

    Callers must check `sharded_splash_ok` first.
    """
    from jax.sharding import PartitionSpec as P
    from areal_tpu.utils.jax_compat import shard_map

    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")

    def local_attn(q, k, v, seg, pos):
        return jax.vmap(
            lambda q1, k1, v1, s1, p1: splash_packed_attention(
                q1, k1, v1, s1, p1,
                softmax_scale=softmax_scale, interpret=interpret,
            )
        )(q, k, v, seg, pos)

    rows = ("data", "fsdp")
    return shard_map(
        local_attn,
        mesh=mesh,
        in_specs=(
            P(rows, None, "tensor", None),
            P(rows, None, "tensor", None),
            P(rows, None, "tensor", None),
            P(rows, None),
            P(rows, None),
        ),
        out_specs=P(rows, None, "tensor", None),
        check_vma=False,
    )(q, k, v, segment_ids, positions)


def cp_axes(mesh) -> tuple:
    """(rows, seq, tensor) sizes of the canonical activation mesh axes —
    the shared prologue of every sharded-attention shape checker."""
    names = mesh.shape
    rows = names.get("data", 1) * names.get("fsdp", 1)
    return rows, names.get("seq", 1), names.get("tensor", 1)


def sharded_splash_ok(mesh, r: int, t: int, hq: int, hkv: int) -> bool:
    """Shapes/mesh divisibility for sharded_splash_attention."""
    rows, _, tensor = cp_axes(mesh)
    return (
        t >= 128
        and t % 128 == 0
        and r % rows == 0
        and hq % tensor == 0
        and hkv % tensor == 0
        and (hq // tensor) % (hkv // tensor) == 0
    )


def resolve_cp_impl(mesh, r: int, t: int, hq: int, hkv: int) -> Optional[str]:
    """Default context-parallel scheme for an 'auto' impl on a seq>1
    mesh (trace-time static decision).

    Policy (analytic default, pending on-ICI measurement — see
    docs/perf_notes.md "ring vs Ulysses" and
    scripts/long_context_probe.py cp mode, which A/Bs this choice):
    prefer Ulysses when the head counts divide the seq axis — its
    per-layer communication is 4 all-to-alls + 2 small gathers
    regardless of the seq size, each moving 1/seq of the activations,
    while ring pays seq pipelined ppermute steps whose overlap with the
    per-chunk kernel is hard to sustain at small chunk sizes. Fall back
    to ring when heads don't divide (GQA with few KV heads on a wide
    seq axis) — ring only needs t % seq == 0. Returns None when neither
    scheme fits (caller keeps its non-CP path)."""
    from areal_tpu.ops.ring_attention import ring_ok
    from areal_tpu.ops.ulysses_attention import ulysses_ok

    if ulysses_ok(mesh, r, t, hq, hkv):
        return "ulysses"
    if ring_ok(mesh, r, t, hq, hkv):
        return "ring"
    return None


def resolve_attn_impl(
    impl: str, t: int, hq: int, hkv: int, mesh=None, r: Optional[int] = None,
) -> str:
    """Resolve 'auto' to a concrete impl for the given shape (trace-time
    static decision). With a seq>1 mesh (and r given), a context-parallel
    scheme is chosen first (resolve_cp_impl); otherwise splash on TPU
    backends when shapes allow, reference as the fallback. Explicit impl
    values pass through untouched."""
    if impl != "auto":
        return impl
    if (
        mesh is not None
        and r is not None
        and mesh.size > 1
        and mesh.shape.get("seq", 1) > 1
    ):
        cp = resolve_cp_impl(mesh, r, t, hq, hkv)
        if cp is not None:
            return cp
    on_tpu = jax.default_backend() in ("tpu", "axon")
    splash_ok = t >= 128 and t % 128 == 0 and hq % hkv == 0
    return "splash" if (on_tpu and splash_ok) else "reference"


def packed_attention(q, k, v, segment_ids, positions, softmax_scale=None, impl="auto"):
    """Dispatch between implementations. Static decision (trace-time): `impl`
    is 'reference', 'flash' (our Pallas kernel), 'splash' (jax's tuned TPU
    kernel), or 'auto' (see resolve_attn_impl)."""
    impl = resolve_attn_impl(impl, q.shape[0], q.shape[1], k.shape[1])
    if impl == "splash":
        return splash_packed_attention(
            q, k, v, segment_ids, positions, softmax_scale=softmax_scale
        )
    if impl == "flash":
        from areal_tpu.ops.pallas.flash_attn import flash_packed_attention

        return flash_packed_attention(
            q, k, v, segment_ids, positions, softmax_scale=softmax_scale
        )
    return reference_packed_attention(
        q, k, v, segment_ids, positions, softmax_scale=softmax_scale
    )

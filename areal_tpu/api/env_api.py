"""Environment service abstraction (reference: realhf/api/core/env_api.py:8)."""

from __future__ import annotations

import abc
from typing import Any, Tuple

from areal_tpu.api.config import EnvServiceAbstraction, Registry


class EnvironmentService(abc.ABC):

    async def reset(self, seed=None, options=None):
        return None, {}

    @abc.abstractmethod
    async def step(self, action: Any) -> Tuple[Any, float, bool, bool, dict]:
        """Gym-style step; for single-step verification envs the action is
        (qid, answer_strs) and the reward list rides in the obs slot."""


ENV_REGISTRY = Registry("environment")


def register_environment(name: str, factory):
    ENV_REGISTRY.register(name, factory)


def make_env(cfg: EnvServiceAbstraction | str, **kwargs) -> EnvironmentService:
    return ENV_REGISTRY.make(cfg, **kwargs)

"""Unit tests for the OpenAI-compatible public wire module
(api/public.py): prompt codec, request validation, SSE framing, and
response envelopes — the transport-free half of the tenant gateway."""

import json

import pytest

from areal_tpu.api import public
from areal_tpu.base.wire_schemas import GATEWAY_V1


def test_byte_codec_roundtrip():
    assert public.encode_text("hi") == [104, 105]
    assert public.decode_tokens([104, 105]) == "hi"
    text = "héllo"
    assert public.decode_tokens(public.encode_text(text)) == text
    # Out-of-byte-range ids are folded for display, never a crash (the
    # authoritative payload is the token_ids field alongside).
    assert public.decode_tokens([65 + 256]) == "A"


def test_parse_completion_defaults():
    p = public.parse_completion_request({"prompt": "hi"})
    assert p.kind == "completion"
    assert p.prompt_ids == [104, 105]
    assert p.max_tokens == 16 and p.stream is True
    assert p.temperature == 1.0 and p.greedy is False
    assert p.session is None and p.model == "areal"


def test_parse_completion_token_ids_and_fields():
    p = public.parse_completion_request({
        "prompt": [1, 2, 3], "max_tokens": 4, "stream": False,
        "temperature": 0.0, "model": "m1", "session": "s1",
    })
    assert p.prompt_ids == [1, 2, 3]
    assert p.max_tokens == 4 and p.stream is False
    assert p.greedy is True  # temperature 0 implies greedy
    assert p.session == "s1" and p.model == "m1"
    # A single-element string list is the OpenAI batched-form of one
    # prompt; real batches are rejected.
    p = public.parse_completion_request({"prompt": ["hi"]})
    assert p.prompt_ids == [104, 105]


@pytest.mark.parametrize("body,frag", [
    ({}, "missing 'prompt'"),
    ({"prompt": ""}, "empty prompt"),
    ({"prompt": ["a", "b"]}, "batched prompts"),
    ({"prompt": 7}, "unsupported prompt type"),
    ({"prompt": "x", "max_tokens": 0}, "max_tokens"),
    ({"prompt": "x", "max_tokens": "lots"}, "bad sampling field"),
    ({"prompt": "x", "n": 2}, "n > 1"),
    ({"prompt": "x", "session": 5}, "session must be a string"),
])
def test_parse_completion_rejects(body, frag):
    with pytest.raises(public.PublicApiError) as ei:
        public.parse_completion_request(body)
    assert ei.value.status == 400
    assert frag in ei.value.message


def test_parse_chat_renders_template():
    p = public.parse_chat_request({"messages": [
        {"role": "system", "content": "be brief"},
        {"role": "user", "content": "hi"},
    ]})
    assert p.kind == "chat"
    text = public.decode_tokens(p.prompt_ids)
    assert text == "system: be brief\nuser: hi\nassistant:"


@pytest.mark.parametrize("body", [
    {}, {"messages": []}, {"messages": "hi"},
    {"messages": [{"role": "user", "content": [1]}]},
])
def test_parse_chat_rejects(body):
    with pytest.raises(public.PublicApiError):
        public.parse_chat_request(body)


def test_sse_framing():
    ev = public.sse_event({"a": 1})
    assert ev == b'data: {"a":1}\n\n'
    assert public.SSE_DONE == b"data: [DONE]\n\n"


def test_completion_chunk_fields():
    c = public.completion_chunk("cmpl-1", "m", [104, 105])
    assert c["schema"] == GATEWAY_V1
    assert c["object"] == "text_completion.chunk"
    ch = c["choices"][0]
    assert ch["text"] == "hi" and ch["token_ids"] == [104, 105]
    assert ch["finish_reason"] is None
    final = public.completion_chunk("cmpl-1", "m", [], "stop")
    assert final["choices"][0]["finish_reason"] == "stop"


def test_chat_chunk_role_on_first_only():
    first = public.chat_chunk("c", "m", [104], first=True)
    later = public.chat_chunk("c", "m", [105])
    assert first["choices"][0]["delta"]["role"] == "assistant"
    assert "role" not in later["choices"][0]["delta"]
    assert later["object"] == "chat.completion.chunk"


def test_bodies_and_usage():
    b = public.completion_body("cmpl-1", "m", [104, 105], 3, "length")
    assert b["usage"] == {
        "prompt_tokens": 3, "completion_tokens": 2, "total_tokens": 5}
    assert b["choices"][0]["finish_reason"] == "length"
    cb = public.chat_body("chatcmpl-1", "m", [104], 2, "stop")
    assert cb["choices"][0]["message"] == {
        "role": "assistant", "content": "h"}
    assert json.loads(json.dumps(cb)) == cb  # wire-serializable


def test_error_body_types():
    assert public.error_body(401, "no")["error"]["type"] == (
        "authentication_error")
    e = public.error_body(429, "slow down", retry_after=1.5)
    assert e["error"]["type"] == "rate_limit_error"
    assert e["error"]["retry_after"] == 1.5
    assert e["schema"] == GATEWAY_V1
    assert public.error_body(503, "down")["error"]["type"] == "api_error"

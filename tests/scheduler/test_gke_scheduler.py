"""Kubernetes (GKE/XPK) scheduler client, driven against a fake kubectl
that runs pods as real local processes (reference analogue: the SLURM
client, realhf/scheduler/slurm/client.py:78, faked at the sbatch level)."""

import json
import os
import signal
import stat
import sys
import time
import uuid

import pytest

from areal_tpu.scheduler.client import JobException, JobState, make_scheduler
from areal_tpu.scheduler.gke import KubernetesSchedulerClient, k8s_name

FAKE = os.path.join(os.path.dirname(__file__), "fake_kubectl.py")


@pytest.fixture()
def kubectl(tmp_path, monkeypatch):
    """Executable fake-kubectl wrapper + isolated cluster state dir."""
    state = tmp_path / "k8s_state"
    monkeypatch.setenv("FAKE_K8S_STATE", str(state))
    wrapper = tmp_path / "kubectl"
    wrapper.write_text(f"#!/bin/sh\nexec {sys.executable} {FAKE} \"$@\"\n")
    wrapper.chmod(wrapper.stat().st_mode | stat.S_IEXEC)
    return str(wrapper), state


def test_k8s_name_sanitization():
    assert k8s_name("model_worker/3") == "model-worker-3"
    assert k8s_name("Rollout Worker/12") == "rollout-worker-12"
    assert len(k8s_name("x" * 100)) <= 63
    assert k8s_name("//") == "job"


def test_manifest_tpu_placement():
    c = KubernetesSchedulerClient(
        container_image="gcr.io/proj/areal:latest",
        tpu_type="tpu-v5-lite-podslice",
        tpu_topology="2x4",
        tpu_chips_per_pod=4,
    )
    m = c._manifest(
        "model-worker-0",
        "model_worker/0",
        ["python", "-m", "areal_tpu.system.worker_main"],
        {"JAX_PLATFORMS": "tpu"},
        "/workdir",
    )
    pod = m["spec"]["template"]["spec"]
    cont = pod["containers"][0]
    assert cont["image"] == "gcr.io/proj/areal:latest"
    assert cont["resources"]["limits"]["google.com/tpu"] == 4
    assert pod["nodeSelector"] == {
        "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
        "cloud.google.com/gke-tpu-topology": "2x4",
    }
    assert m["spec"]["backoffLimit"] == 0  # relaunch loop owns recovery
    assert pod["restartPolicy"] == "Never"
    assert {"name": "JAX_PLATFORMS", "value": "tpu"} in cont["env"]


def test_submit_wait_completed(kubectl):
    cmd, _ = kubectl
    c = make_scheduler("gke", kubectl_cmd=cmd)
    c.submit("worker/0", [sys.executable, "-c", "print('ok')"])
    infos = c.wait(timeout=30, poll_interval=0.1)
    assert [i.state for i in infos] == [JobState.COMPLETED]


def test_submit_failure_raises(kubectl):
    cmd, _ = kubectl
    c = make_scheduler("gke", kubectl_cmd=cmd)
    c.submit("worker/0", [sys.executable, "-c", "raise SystemExit(3)"])
    with pytest.raises(JobException):
        c.wait(timeout=30, poll_interval=0.1)
    assert c.find("worker/0").state == JobState.FAILED


def test_killed_pod_reads_as_failed(kubectl):
    """A pod killed out-of-band (lost node) must surface as FAILED even
    though no exit code was ever recorded."""
    cmd, state = kubectl
    c = make_scheduler("gke", kubectl_cmd=cmd)
    c.submit("worker/0", [sys.executable, "-c", "import time; time.sleep(60)"])
    deadline = time.monotonic() + 10
    while c.find("worker/0").state != JobState.RUNNING:
        assert time.monotonic() < deadline
        time.sleep(0.05)
    with open(state / "worker-0.json") as f:
        pid = json.load(f)["pid"]
    os.killpg(pid, signal.SIGKILL)
    deadline = time.monotonic() + 10
    while c.find("worker/0").state != JobState.FAILED:
        assert time.monotonic() < deadline
        time.sleep(0.05)


def test_stop_and_resubmit(kubectl):
    cmd, _ = kubectl
    c = make_scheduler("gke", kubectl_cmd=cmd)
    c.submit("worker/0", [sys.executable, "-c", "import time; time.sleep(60)"])
    c.stop("worker/0")
    assert c.find("worker/0").state == JobState.NOT_FOUND
    # Same-name resubmission (recovery relaunch) replaces the old job.
    c.submit("worker/0", [sys.executable, "-c", "print('again')"])
    infos = c.wait(["worker/0"], timeout=30, poll_interval=0.1)
    assert infos[0].state == JobState.COMPLETED
    c.stop_all()


def _sft_mock_cfg(exp, trial, tmp_path, benchmark_steps, recover_mode):
    from areal_tpu.api.config import (
        DatasetAbstraction,
        ModelAbstraction,
        ModelBackendAbstraction,
        ModelInterfaceAbstraction,
        ModelName,
        ModelShardID,
    )
    from areal_tpu.api.data_api import MicroBatchSpec
    from areal_tpu.api.dfg import MFCDef, ModelInterfaceType
    from areal_tpu.api.system_api import (
        ExperimentConfig,
        ExperimentSaveEvalControl,
        MasterWorkerConfig,
        ModelShardSpec,
        ModelWorkerConfig,
    )
    from tests import fixtures

    tiny = dict(
        vocab_size=128, hidden_dim=32, n_layers=2, n_q_heads=2, n_kv_heads=1,
        head_dim=16, intermediate_dim=64, max_position_embeddings=256,
        compute_dtype="float32",
    )
    tok_dir = str(tmp_path / "tok_full")
    data_path = str(tmp_path / "sft.jsonl")
    if not os.path.exists(tok_dir):
        rows = fixtures.make_sft_rows(32, seed=3)
        tok = fixtures.train_tiny_tokenizer(
            [r["prompt"] + " " + r["answer"] for r in rows], tmp_path
        )
        tok.save_pretrained(tok_dir)
        fixtures.write_jsonl(rows, tmp_path / "sft.jsonl")
    sft = MFCDef(
        name="sft_train",
        model_name=ModelName("default", 0),
        interface_type=ModelInterfaceType.TRAIN_STEP,
        interface_impl=None,
        n_seqs=8,
        input_keys=("packed_input_ids", "prompt_mask"),
        mb_spec=MicroBatchSpec(n_mbs=1),
    )
    mw = ModelWorkerConfig(
        experiment_name=exp,
        trial_name=trial,
        worker_index=0,
        shards=[
            ModelShardSpec(
                id=ModelShardID(ModelName("default", 0)),
                model=ModelAbstraction(
                    "tpu_transformer",
                    args=dict(config=tiny, tokenizer_path=tok_dir),
                ),
                backend=ModelBackendAbstraction("mock_train"),
                interface=ModelInterfaceAbstraction("sft"),
            )
        ],
        datasets=[
            DatasetAbstraction(
                "prompt_answer", args=dict(max_length=64, dataset_path=data_path)
            )
        ],
        tokenizer_path=tok_dir,
        train_batch_size=8,
        total_train_epochs=50,
    )
    master = MasterWorkerConfig(
        experiment_name=exp,
        trial_name=trial,
        exp_ctrl=ExperimentSaveEvalControl(
            total_train_epochs=50,
            ckpt_freq_steps=2,
            benchmark_steps=benchmark_steps,
        ),
        rpcs=[sft],
        model_topos={str(ModelName("default", 0)): ["model_worker/0"]},
        data_hosts=["model_worker/0"],
        n_model_workers=1,
        train_batch_size=8,
        recover_mode=recover_mode,
    )
    return ExperimentConfig(
        experiment_name=exp, trial_name=trial, master=master, model_workers=[mw]
    )


@pytest.mark.slow  # ~55s kill-and-relaunch e2e; the fake-kubectl unit
# coverage above stays in tier-1
def test_cluster_controller_gke_e2e_failure_then_recovery(kubectl, tmp_path):
    """ClusterController on the gke scheduler: pods run the real worker
    processes; a pod killed mid-run surfaces as a scheduler failure, and
    the relaunch-with-recovery path finishes the experiment (VERDICT r3
    missing #3 done-criterion)."""
    import threading

    from areal_tpu.system.controller import ClusterController

    cmd, state = kubectl
    exp, trial = f"gke-rec-{uuid.uuid4().hex[:6]}", "t0"
    env = {
        "JAX_PLATFORMS": "cpu",
        "AREAL_FILEROOT": str(tmp_path / "fileroot"),
        "FAKE_K8S_STATE": str(state),
    }

    def make_ctl(benchmark_steps, recover_mode):
        return ClusterController(
            _sft_mock_cfg(exp, trial, tmp_path, benchmark_steps, recover_mode),
            spool_dir=str(tmp_path / "spool"),
            scheduler_mode="gke",
            scheduler_kwargs={"kubectl_cmd": cmd},
            worker_env=env,
        )

    # The master runs inline in THIS process, so recover info lands under
    # this process's fileroot, not the workers' AREAL_FILEROOT.
    from areal_tpu.base import recover

    recover_file = recover.dump_path(exp, trial)

    # ClusterController scopes cluster job names per experiment/trial.
    job = k8s_name(f"{exp}-{trial}-model_worker/0")

    def kill_pod_after_first_ckpt():
        deadline = time.monotonic() + 120
        while not os.path.exists(recover_file):
            if time.monotonic() > deadline:
                return
            time.sleep(0.2)
        with open(state / f"{job}.json") as f:
            pid = json.load(f)["pid"]
        os.killpg(pid, signal.SIGKILL)

    killer = threading.Thread(target=kill_pod_after_first_ckpt, daemon=True)
    killer.start()
    with pytest.raises(RuntimeError):
        make_ctl(benchmark_steps=200, recover_mode="disabled").run()
    killer.join(timeout=130)
    assert os.path.exists(recover_file)  # failure happened after a checkpoint

    # Relaunch with recovery: resumes past the checkpoint and completes.
    resumed_from = recover.load(exp, trial).last_step_info.global_step
    target = resumed_from + 4
    result = make_ctl(benchmark_steps=target, recover_mode="auto").run()
    assert result["global_step"] == target


def test_name_prefix_scopes_jobs(kubectl):
    """Two trials sharing a namespace must not collide on worker names."""
    cmd, state = kubectl
    a = make_scheduler("gke", kubectl_cmd=cmd, name_prefix="expA-t0")
    b = make_scheduler("gke", kubectl_cmd=cmd, name_prefix="expB-t0")
    a.submit("worker/0", [sys.executable, "-c", "import time; time.sleep(30)"])
    b.submit("worker/0", [sys.executable, "-c", "print('done')"])
    # B's submit (and its stale-job cleanup) must not have touched A.
    assert a.find("worker/0").state == JobState.RUNNING
    b.wait(["worker/0"], timeout=30, poll_interval=0.1)
    assert a.find("worker/0").state == JobState.RUNNING
    a.stop_all()
    b.stop_all()

"""Single-step math/code RL agent.

Counterpart of the reference's math single-step agent
(realhf/impl/agent/math_single_step_agent.py:44-248): one prompt -> one
group of generations -> verifier rewards -> one trajectory sample. The
obs/act queue protocol is kept: the agent never talks HTTP itself.
Degenerate groups (success rate outside [lb, ub]) are dropped
(reference :95-103).
"""

from __future__ import annotations

import asyncio
from typing import Any, List, Optional

import numpy as np

from areal_tpu.api.agent_api import Agent, register_agent
from areal_tpu.api.data_api import SequenceSample
from areal_tpu.api.env_api import EnvironmentService
from areal_tpu.api.model_api import BundledGenerationOutputs, GenerationHyperparameters
from areal_tpu.base import logging

logger = logging.getLogger("math_agent")


class MathSingleStepAgent(Agent):
    def __init__(
        self,
        gconfig: Optional[GenerationHyperparameters] = None,
        tokenizer: Any = None,
        reward_scaling: float = 1.0,
        reward_bias: float = 0.0,
        correct_reward: float = 5.0,
        wrong_reward: float = -5.0,
        success_rate_lb: float = 0.0,
        success_rate_ub: float = 1.0,
        **gconfig_kwargs,
    ):
        if gconfig is None:
            gconfig = GenerationHyperparameters(**gconfig_kwargs)
        elif isinstance(gconfig, dict):
            gconfig = GenerationHyperparameters(**gconfig)
        self.gconfig = gconfig
        self.tokenizer = tokenizer
        self.reward_scaling = reward_scaling
        self.reward_bias = reward_bias
        self.correct_reward = correct_reward
        self.wrong_reward = wrong_reward
        self.success_rate_lb = success_rate_lb
        self.success_rate_ub = success_rate_ub

    async def collect_trajectory(
        self,
        prompt: SequenceSample,
        env: EnvironmentService,
        obs_queue: asyncio.Queue,
        act_queue: asyncio.Queue,
    ) -> List[SequenceSample]:
        assert prompt.bs == 1
        qid = prompt.ids[0]
        prompt_ids = np.asarray(prompt.data["packed_prompts"]).tolist()
        await obs_queue.put((qid, prompt_ids, self.gconfig))
        bundle: BundledGenerationOutputs = await act_queue.get()

        task = (prompt.metadata.get("tasks") or ["math"])[0]
        answer_info = (prompt.metadata.get("solutions") or [None])[0]
        answers = [
            self.tokenizer.decode(seq[bundle.prompt_len:])
            for seq in bundle.seqs
        ]
        successes, *_ = await env.step((qid, answers, task, answer_info))

        sr = float(np.mean(successes)) if successes else 0.0
        if not (self.success_rate_lb <= sr <= self.success_rate_ub):
            logger.debug(f"{qid}: degenerate group (sr={sr:.2f}), dropped")
            return []

        rewards = np.asarray(
            [
                (self.correct_reward if ok else self.wrong_reward)
                * self.reward_scaling
                + self.reward_bias
                for ok in successes
            ],
            np.float32,
        )
        from areal_tpu.agents.common import bundle_to_sample

        return [bundle_to_sample(qid, bundle, rewards, score=sr, task=task)]


register_agent("math-single-step", MathSingleStepAgent)

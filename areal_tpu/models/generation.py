"""In-framework generation: jitted prefill + KV-cache decode loop.

Counterpart of the reference's generation engine
(realhf/impl/model/nn/real_llm_generate.py): token-by-token decode with a
preallocated KV cache and on-device sampling. The reference needs CUDA
graph capture (`maybe_capture_cudagraph:218`) to make tiny decode kernels
fast; on TPU the whole decode step is one jitted XLA program with donated
cache buffers, so no capture machinery exists at all.

Cache layout: k/v as [L, B, S, Hkv, hd] matching the scan-over-layers
parameter stacking. Batch entries are independent sequences (generation is
not packed; packing happens on training inputs).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from areal_tpu.models.config import TransformerConfig
from areal_tpu.models.transformer import forward as packed_forward
from areal_tpu.models.transformer import _norm, _mlp
from areal_tpu.ops.attention import decode_attention
from areal_tpu.ops.rotary import apply_rotary, rotary_cos_sin, rotary_inv_freq
from areal_tpu.ops.norms import rms_norm
from areal_tpu.ops.sampling import sample_token


@dataclasses.dataclass
class GenState:
    """Decode-loop carry (a pytree)."""

    rng: jax.Array
    k_cache: jnp.ndarray  # [L, B, S, Hkv, hd]
    v_cache: jnp.ndarray
    lengths: jnp.ndarray  # [B] tokens currently in cache (incl. prompt)
    logits: jnp.ndarray  # [B, V] for the next sampling step
    out_tokens: jnp.ndarray  # [B, max_new]
    out_logprobs: jnp.ndarray  # [B, max_new]
    done: jnp.ndarray  # [B] bool
    step: jnp.ndarray  # scalar int32


jax.tree_util.register_dataclass(
    GenState,
    data_fields=[
        "rng", "k_cache", "v_cache", "lengths", "logits",
        "out_tokens", "out_logprobs", "done", "step",
    ],
    meta_fields=[],
)


def _decode_layer(x, lp, cfg, cos, sin, k_cache_l, v_cache_l, lengths, cdt):
    """One transformer layer for a single new token per sequence.

    x: [B, D]; k/v_cache_l: [B, S, Hkv, hd]; lengths: [B] count *before*
    this token. Returns (x, new_k_cache_l, new_v_cache_l).
    """
    B, D = x.shape
    h = _norm(x, lp["ln1"], cfg)
    a = lp["attn"]
    q = h @ a["wq"].astype(cdt)
    k = h @ a["wk"].astype(cdt)
    v = h @ a["wv"].astype(cdt)
    if "bq" in a:
        q = q + a["bq"].astype(cdt)
        k = k + a["bk"].astype(cdt)
        v = v + a["bv"].astype(cdt)
    q = q.reshape(B, cfg.n_q_heads, cfg.head_dim)
    k = k.reshape(B, cfg.n_kv_heads, cfg.head_dim)
    v = v.reshape(B, cfg.n_kv_heads, cfg.head_dim)
    if cfg.qk_norm:
        q = rms_norm(q, a["q_norm"], cfg.norm_eps)
        k = rms_norm(k, a["k_norm"], cfg.norm_eps)
    if cos is not None:
        # cos/sin: [B, hd/2] at the current position of each row.
        q = apply_rotary(q, cos, sin, cfg.rotary_interleaved)
        k = apply_rotary(k, cos, sin, cfg.rotary_interleaved)
    b_idx = jnp.arange(B)
    k_cache_l = k_cache_l.at[b_idx, lengths].set(k)
    v_cache_l = v_cache_l.at[b_idx, lengths].set(v)
    out = decode_attention(q, k_cache_l, v_cache_l, lengths + 1)
    attn_out = out.reshape(B, cfg.q_dim) @ a["wo"].astype(cdt)
    if "bo" in a:
        attn_out = attn_out + a["bo"].astype(cdt)
    x = x + attn_out
    h = _norm(x, lp["ln2"], cfg)
    if cfg.moe is not None:
        from areal_tpu.models.moe import decode_moe_overrides, moe_mlp

        # Same decode-time dispatch/capacity as engine/paged.py, so the
        # batch generator and the paged server produce identical greedy
        # streams for MoE models.
        d_dispatch, d_cap = decode_moe_overrides(cfg)
        m, _ = moe_mlp(
            h, lp["mlp"], cfg, cdt,
            capacity_factor=d_cap, dispatch=d_dispatch,
        )
    else:
        m = _mlp(h, lp["mlp"], cfg, cdt)
    x = x + m
    return x, k_cache_l, v_cache_l


def decode_step(params, cfg: TransformerConfig, tokens, k_cache, v_cache, lengths):
    """One decode step for all sequences.

    tokens: [B] the tokens just sampled (to be fed in); lengths: [B] cache
    fill BEFORE this token. Returns (logits [B, V], k_cache, v_cache).
    """
    cdt = jnp.dtype(cfg.compute_dtype)
    x = params["embedding"]["weight"][tokens].astype(cdt)  # [B, D]
    if cfg.embedding_multiplier:
        x = x * jnp.asarray(cfg.embedding_multiplier, cdt)
    if cfg.pos_emb == "learned":
        x = x + params["pos_embedding"]["weight"][lengths].astype(cdt)
        cos = sin = None
    else:
        inv_freq = jnp.asarray(
            rotary_inv_freq(
                cfg.head_dim, cfg.rotary_base, cfg.rotary_scaling,
                cfg.rotary_scaling_type, cfg.rotary_scaling_params,
            )
        )
        cos, sin = rotary_cos_sin(lengths, inv_freq)  # [B, hd/2]

    def body(x, layer):
        lp, kc, vc = layer
        x, kc, vc = _decode_layer(x, lp, cfg, cos, sin, kc, vc, lengths, cdt)
        return x, (kc, vc)

    x, (k_cache, v_cache) = jax.lax.scan(body, x, (params["layers"], k_cache, v_cache))
    x = _norm(x, params["final_norm"], cfg)
    head_w = (
        params["embedding"]["weight"].T
        if cfg.tied_embeddings
        else params["head"]["weight"]
    )
    logits = (x @ head_w.astype(cdt)).astype(jnp.float32)
    return logits, k_cache, v_cache


def prefill(params, cfg: TransformerConfig, input_ids, prompt_lens, cache_len: int):
    """Run the prompt forward, build the KV cache.

    input_ids: [B, P] right-padded prompts; prompt_lens: [B].
    Returns (last_logits [B, V], k_cache, v_cache) with caches sized
    [L, B, cache_len, Hkv, hd].
    """
    B, P = input_ids.shape
    pos = jnp.arange(P)[None, :]
    seg = (pos < prompt_lens[:, None]).astype(jnp.int32)
    positions = jnp.where(seg > 0, pos, 0).astype(jnp.int32)
    logits, kvs = packed_forward(
        params, cfg, input_ids, seg, positions, return_kv=True
    )
    # kvs: (k, v) each [L, B, P, Hkv, hd]
    k_pref, v_pref = kvs
    L = k_pref.shape[0]
    Hkv, hd = k_pref.shape[-2], k_pref.shape[-1]
    cdt = k_pref.dtype
    k_cache = jnp.zeros((L, B, cache_len, Hkv, hd), cdt)
    v_cache = jnp.zeros((L, B, cache_len, Hkv, hd), cdt)
    k_cache = k_cache.at[:, :, :P].set(k_pref)
    v_cache = v_cache.at[:, :, :P].set(v_pref)
    last_idx = jnp.maximum(prompt_lens - 1, 0)
    last_logits = jnp.take_along_axis(
        logits, last_idx[:, None, None], axis=1
    )[:, 0]  # [B, V]
    return last_logits, k_cache, v_cache


# Module-level jit so the compile cache survives across generate calls.
_prefill_jit = jax.jit(prefill, static_argnames=("cfg", "cache_len"))


@functools.partial(
    jax.jit,
    static_argnames=(
        "cfg", "max_new_tokens", "min_new_tokens", "greedy",
        "top_k", "stop_tokens",
    ),
    donate_argnames=("k_cache", "v_cache"),
)
def _decode_loop(
    params,
    cfg: TransformerConfig,
    first_logits,
    k_cache,
    v_cache,
    prompt_lens,
    rng,
    max_new_tokens: int,
    min_new_tokens: int,
    greedy: bool,
    top_k: int,
    top_p,
    temperature,
    stop_tokens: Tuple[int, ...],
):
    B = first_logits.shape[0]
    stop_arr = jnp.asarray(stop_tokens, jnp.int32) if stop_tokens else None
    state = GenState(
        rng=rng,
        k_cache=k_cache,
        v_cache=v_cache,
        lengths=prompt_lens,
        logits=first_logits,
        out_tokens=jnp.zeros((B, max_new_tokens), jnp.int32),
        out_logprobs=jnp.zeros((B, max_new_tokens), jnp.float32),
        done=jnp.zeros((B,), bool),
        step=jnp.asarray(0, jnp.int32),
    )

    def cond(s: GenState):
        return (s.step < max_new_tokens) & ~jnp.all(s.done)

    def body(s: GenState):
        rng, sub = jax.random.split(s.rng)
        forbid_mask = (
            jnp.full((B,), s.step < min_new_tokens) if min_new_tokens > 0 else None
        )
        tokens, logprobs = sample_token(
            s.logits, sub, greedy=greedy, temperature=temperature,
            top_k=top_k, top_p=top_p,
            forbid_token_ids=stop_arr if min_new_tokens > 0 else None,
            forbid_mask=forbid_mask,
        )
        hit_stop = (
            jnp.isin(tokens, stop_arr) if stop_arr is not None
            else jnp.zeros((B,), bool)
        )
        # Rows already done keep emitting pad (token 0) that we mask out.
        emit = jnp.where(s.done, 0, tokens).astype(jnp.int32)
        out_tokens = s.out_tokens.at[:, s.step].set(emit)
        out_logprobs = s.out_logprobs.at[:, s.step].set(
            jnp.where(s.done, 0.0, logprobs)
        )
        logits, kc, vc = decode_step(
            params, cfg, emit, s.k_cache, s.v_cache, s.lengths
        )
        return GenState(
            rng=rng,
            k_cache=kc,
            v_cache=vc,
            lengths=s.lengths + jnp.where(s.done, 0, 1).astype(s.lengths.dtype),
            logits=logits,
            out_tokens=out_tokens,
            out_logprobs=out_logprobs,
            done=s.done | hit_stop,
            step=s.step + 1,
        )

    final = jax.lax.while_loop(cond, body, state)
    return final.out_tokens, final.out_logprobs, final.lengths, final.done


def generate_tokens(
    params,
    cfg: TransformerConfig,
    prompts: List[List[int]],
    gconfig,
    rng: jax.Array,
    eos_token_id: Optional[int] = None,
    prompt_pad_multiple: int = 64,
) -> List[Dict[str, Any]]:
    """Host-facing generation over a batch of prompts.

    Returns per-prompt dicts: output_ids, output_logprobs, no_eos.
    """
    B = len(prompts)
    plens = np.array([len(p) for p in prompts], np.int32)
    P = int(
        -(-max(int(plens.max()), 1) // prompt_pad_multiple) * prompt_pad_multiple
    )
    input_ids = np.zeros((B, P), np.int32)
    for i, p in enumerate(prompts):
        input_ids[i, : len(p)] = p
    cache_len = P + gconfig.max_new_tokens

    stop = tuple(gconfig.stop_token_ids)
    if eos_token_id is not None and eos_token_id not in stop:
        stop = stop + (eos_token_id,)

    first_logits, k_cache, v_cache = _prefill_jit(
        params, cfg, jnp.asarray(input_ids), jnp.asarray(plens), cache_len=cache_len
    )
    out_tokens, out_logprobs, lengths, done = _decode_loop(
        params, cfg, first_logits, k_cache, v_cache, jnp.asarray(plens), rng,
        max_new_tokens=gconfig.max_new_tokens,
        min_new_tokens=gconfig.min_new_tokens,
        greedy=gconfig.greedy,
        top_k=gconfig.top_k,
        top_p=jnp.asarray(gconfig.top_p, jnp.float32),
        temperature=jnp.asarray(gconfig.temperature, jnp.float32),
        stop_tokens=stop,
    )
    out_tokens = np.asarray(out_tokens)
    out_logprobs = np.asarray(out_logprobs)
    gen_lens = np.asarray(lengths) - plens
    done = np.asarray(done)
    results = []
    for i in range(B):
        # `lengths` advances on the step that emits the stop token, so
        # gen_lens already counts it (reference convention: EOS terminates
        # the sequence and is part of the output).
        n = int(gen_lens[i])
        results.append(
            {
                "output_ids": out_tokens[i, :n].tolist(),
                "output_logprobs": out_logprobs[i, :n].tolist(),
                "no_eos": not bool(done[i]),
            }
        )
    return results

"""FLOP accounting + profiling utilities (reference: flops_counter/monitor)."""

import os

import numpy as np
import pytest

from areal_tpu.base import monitor
from areal_tpu.models.config import TransformerConfig
from areal_tpu.system.model_function_call import merge_worker_stats
from areal_tpu.base import stats_tracker
from areal_tpu.utils import profiling


def small_cfg(**kw):
    return TransformerConfig(
        n_layers=2, hidden_dim=32, n_q_heads=4, n_kv_heads=2, head_dim=8,
        intermediate_dim=64, vocab_size=128, **kw,
    )


def test_transformer_forward_flops_manual():
    cfg = small_cfg()
    seqlens = [10, 20]
    T = 30
    q_dim, kv_dim = 32, 16
    attn_proj = 2 * T * 32 * (2 * q_dim + 2 * kv_dim)
    attn_quad = 4 * (100 + 400) * q_dim
    mlp = 2 * T * 32 * 64 * 3
    head = 2 * T * 32 * 128
    expected = 2 * (attn_proj + attn_quad + mlp) + head
    assert monitor.transformer_forward_flops(cfg, seqlens) == expected


def test_mfc_flops_interface_scaling():
    cfg = small_cfg()
    f1 = monitor.mfc_flops(cfg, "inference", [16, 16])
    f3 = monitor.mfc_flops(cfg, "train_step", [16, 16])
    assert f3 == 3 * f1
    # generate counts the full sequences (prompt + generation)
    fg = monitor.mfc_flops(cfg, "generate", [4, 4], [16, 16])
    assert fg == monitor.transformer_forward_flops(cfg, [16, 16])


def test_llama_formula_renamed():
    # VERDICT r1: the reference's misspelled name must not be carried over.
    assert not hasattr(monitor, "caculuate_llama_forward_flops")
    v = monitor.calculate_llama_forward_flops(
        1, [8], hidden_size=32, intermediate_size=64, vocab_size=128,
        n_layers=2, num_heads=4, num_kv_heads=2,
    )
    assert v > 0
    assert monitor.calculate_llama_train_flops(
        1, [8], hidden_size=32, intermediate_size=64, vocab_size=128,
        n_layers=2, num_heads=4, num_kv_heads=2,
    ) == 3 * v


def test_stats_tracker_export_types():
    t = stats_tracker.DistributedStatsTracker()
    t.denominator(n_valid=np.array([True, True, False]))
    t.stat("n_valid", stats_tracker.ReduceType.AVG, loss=np.array([1.0, 2.0, 9.0]))
    t.stat("n_valid", stats_tracker.ReduceType.MAX, peak=np.array([1.0, 5.0, 9.0]))
    t.scalar(lr=0.1)
    stats, types = t.export(return_types=True)
    assert stats["n_valid"] == 2.0 and types["n_valid"] == "sum"
    assert stats["loss"] == 1.5 and types["loss"] == "avg"
    assert stats["peak"] == 5.0 and types["peak"] == "max"
    assert types["lr"] == "avg"


def test_merge_worker_stats_semantics():
    a = {"loss": 1.0, "x/n_tokens": 10.0, "perf/flops": 100.0, "perf/sec": 1.0}
    b = {"loss": 3.0, "x/n_tokens": 30.0, "perf/flops": 300.0, "perf/sec": 2.0}
    m = merge_worker_stats([a, b])
    assert m["loss"] == 2.0  # avg
    assert m["x/n_tokens"] == 40.0  # sum by suffix
    assert m["perf/flops"] == 400.0  # sum
    assert m["perf/sec"] == 2.0  # max (concurrent workers)
    # declared types override the heuristic
    a["__reduce_types__"] = {"loss": "sum"}
    m = merge_worker_stats([a, b])
    assert m["loss"] == 4.0


@pytest.mark.slow  # ~24s: profiler capture round-trip; noop path is cheap
def test_maybe_profile_noop_and_capture(tmp_path, monkeypatch):
    # disabled: no-op
    monkeypatch.delenv("AREAL_DUMP_TRACE", raising=False)
    with profiling.maybe_profile("mfc_x", step=3):
        pass
    # enabled: creates the dump dir (jax.profiler trace on CPU)
    monkeypatch.setenv("AREAL_DUMP_TRACE", "1")
    monkeypatch.setenv("AREAL_TRACE_DIR", str(tmp_path))
    monkeypatch.setenv("AREAL_TRACE_STEPS", "3")
    with profiling.maybe_profile("mfc_x", step=2):  # step not selected
        pass
    assert not (tmp_path / "mfc_x" / "step2").exists()
    with profiling.maybe_profile("mfc_x", step=3):
        import jax.numpy as jnp

        (jnp.ones(8) * 2).block_until_ready()
    assert (tmp_path / "mfc_x" / "step3").exists()


def test_time_marks():
    tm = profiling.TimeMarks()
    with tm.record("fwd"):
        pass
    with tm.record("fwd"):
        pass
    out = tm.export()
    assert "timeperf/fwd" in out and out["timeperf/fwd"] >= 0.0
    assert tm.export() == {}


# ----------------------------------------------------------------------
# Device memory telemetry + OOM guard (reference model_worker.py:1507-1610)
# ----------------------------------------------------------------------


class _FakeDev:
    def __init__(self, stats):
        self._stats = stats

    def memory_stats(self):
        return self._stats


def test_device_memory_stats_aggregates():
    devs = [
        _FakeDev({"bytes_in_use": 100, "bytes_limit": 1000,
                  "peak_bytes_in_use": 300}),
        _FakeDev({"bytes_in_use": 200, "bytes_limit": 1000,
                  "peak_bytes_in_use": 400}),
        _FakeDev(None),  # backend without stats
    ]
    s = monitor.device_memory_stats(devs)
    assert s["mem_bytes_in_use"] == 300
    assert s["mem_bytes_limit"] == 2000
    assert s["mem_peak_bytes_in_use"] == 700
    assert s["mem_frac_in_use"] == pytest.approx(0.15)
    assert s["mem_devices_reporting"] == 2


def test_device_memory_stats_no_backend_support():
    s = monitor.device_memory_stats([_FakeDev(None)])
    assert s["mem_bytes_limit"] == 0 and s["mem_frac_in_use"] == 0.0


def test_memory_kill_threshold(monkeypatch):
    devs = [_FakeDev({"bytes_in_use": 950, "bytes_limit": 1000})]
    # Unset env: never raises.
    monkeypatch.delenv(monitor.MEMORY_KILL_THRESHOLD_ENV, raising=False)
    monitor.check_memory_kill_threshold(devices=devs)
    # Over threshold: raises for relaunch-recovery.
    monkeypatch.setenv(monitor.MEMORY_KILL_THRESHOLD_ENV, "0.9")
    with pytest.raises(monitor.DeviceOOMGuardError, match="kill threshold"):
        monitor.check_memory_kill_threshold(devices=devs)
    # Under threshold: fine.
    monkeypatch.setenv(monitor.MEMORY_KILL_THRESHOLD_ENV, "0.99")
    monitor.check_memory_kill_threshold(devices=devs)
    # No stats reported: guard is a no-op even with env set.
    monkeypatch.setenv(monitor.MEMORY_KILL_THRESHOLD_ENV, "0.1")
    monitor.check_memory_kill_threshold(devices=[_FakeDev(None)])

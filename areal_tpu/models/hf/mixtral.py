"""Mixtral HF conversion: mistral attention + MoE FFN.
Reference parity: realhf/api/from_hf/mixtral.py.

HF layout: per-layer `block_sparse_moe.gate.weight` [E, D] router and
`block_sparse_moe.experts.{e}.w1/w3/w2` (gate/up/down, each [F, D] or
[D, F]); stacked here into router [L, D, E] and expert weights
[L, E, D, F] / [L, E, F, D] matching `areal_tpu.models.moe`.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from areal_tpu.api.model_api import register_hf_family
from areal_tpu.models.config import MoEConfig, TransformerConfig
from areal_tpu.models.hf import HFFamily
from areal_tpu.models.hf.llama import (
    _config_from_hf as llama_config_from_hf,
    _config_to_hf as llama_config_to_hf,
)


def _config_from_hf(hf: Dict[str, Any], is_critic: bool = False) -> TransformerConfig:
    cfg = llama_config_from_hf(hf, is_critic)
    E = hf.get("num_local_experts", 8)
    k = hf.get("num_experts_per_tok", 2)
    cfg.moe = MoEConfig(
        num_experts=E,
        top_k=k,
        # HF Mixtral routes exactly (no capacity drops); E/k guarantees the
        # einsum dispatch never drops either, so logits match. Users can
        # lower this for speed once drops are acceptable.
        capacity_factor=float(E) / k,
        aux_loss_coef=hf.get("router_aux_loss_coef", 1e-2),
    )
    return cfg


def _config_to_hf(cfg: TransformerConfig) -> Dict[str, Any]:
    hf = llama_config_to_hf(cfg)
    hf["architectures"] = ["MixtralForCausalLM"]
    hf["model_type"] = "mixtral"
    hf["num_local_experts"] = cfg.moe.num_experts
    hf["num_experts_per_tok"] = cfg.moe.top_k
    hf["router_aux_loss_coef"] = cfg.moe.aux_loss_coef
    hf.pop("attention_bias", None)
    return hf


def _params_from_hf(sd: Dict[str, np.ndarray], cfg: TransformerConfig) -> Dict:
    from areal_tpu.models.hf.llama import params_from_hf_llama_style

    E, L = cfg.moe.num_experts, cfg.n_layers

    # Reuse the llama importer for everything but the FFN by aliasing the
    # expert-0 weights into the dense keys it expects, then overwrite.
    alias = dict(sd)
    for i in range(L):
        base = f"model.layers.{i}.block_sparse_moe"
        alias[f"model.layers.{i}.mlp.gate_proj.weight"] = sd[f"{base}.experts.0.w1.weight"]
        alias[f"model.layers.{i}.mlp.up_proj.weight"] = sd[f"{base}.experts.0.w3.weight"]
        alias[f"model.layers.{i}.mlp.down_proj.weight"] = sd[f"{base}.experts.0.w2.weight"]
    params = params_from_hf_llama_style(alias, cfg)

    def t(name):
        return np.ascontiguousarray(sd[name].astype(np.float32).T)

    params["layers"]["mlp"] = {
        "router": np.stack(
            [t(f"model.layers.{i}.block_sparse_moe.gate.weight") for i in range(L)]
        ),  # [L, D, E]
        "w_gate": np.stack(
            [
                np.stack(
                    [t(f"model.layers.{i}.block_sparse_moe.experts.{e}.w1.weight") for e in range(E)]
                )
                for i in range(L)
            ]
        ),  # [L, E, D, F]
        "w_up": np.stack(
            [
                np.stack(
                    [t(f"model.layers.{i}.block_sparse_moe.experts.{e}.w3.weight") for e in range(E)]
                )
                for i in range(L)
            ]
        ),
        "w_down": np.stack(
            [
                np.stack(
                    [t(f"model.layers.{i}.block_sparse_moe.experts.{e}.w2.weight") for e in range(E)]
                )
                for i in range(L)
            ]
        ),  # [L, E, F, D]
    }
    return params


def _params_to_hf(params: Dict, cfg: TransformerConfig) -> Dict[str, np.ndarray]:
    from areal_tpu.models.hf.llama import params_to_hf_llama_style

    E, L = cfg.moe.num_experts, cfg.n_layers
    m = params["layers"]["mlp"]
    # Give the llama exporter dense-shaped placeholders, then replace.
    dense_view = dict(params)
    dense_view["layers"] = dict(params["layers"])
    dense_view["layers"]["mlp"] = {
        "w_gate": np.asarray(m["w_gate"])[:, 0],
        "w_up": np.asarray(m["w_up"])[:, 0],
        "w_down": np.asarray(m["w_down"])[:, 0],
    }
    sd = params_to_hf_llama_style(dense_view, cfg)
    for i in range(L):
        base = f"model.layers.{i}.block_sparse_moe"
        for k in ("mlp.gate_proj.weight", "mlp.up_proj.weight", "mlp.down_proj.weight"):
            sd.pop(f"model.layers.{i}.{k}", None)
        sd[f"{base}.gate.weight"] = np.asarray(m["router"][i]).T
        for e in range(E):
            sd[f"{base}.experts.{e}.w1.weight"] = np.asarray(m["w_gate"][i, e]).T
            sd[f"{base}.experts.{e}.w3.weight"] = np.asarray(m["w_up"][i, e]).T
            sd[f"{base}.experts.{e}.w2.weight"] = np.asarray(m["w_down"][i, e]).T
    return sd


register_hf_family(
    "mixtral",
    HFFamily(
        name="mixtral",
        hf_model_type="mixtral",
        config_from_hf=_config_from_hf,
        config_to_hf=_config_to_hf,
        params_from_hf=_params_from_hf,
        params_to_hf=_params_to_hf,
    ),
)

"""ISSUE 9 acceptance (bench leg): the `train_sharded` phase banks an
attested CPU-proxy record with loss-trajectory parity (single-device vs
FSDP2 vs TP2 fake-device meshes), the per-mesh step-time breakdown, and
the shard-local dump's host high-water reduced ~1/mesh_size with a
byte-identical weight-plane round trip — and `validate_bench.py`
refuses records lacking the parity / scaling / high-water fields.

Loss parity and sha256 byte accounting are exact and machine
independent, which is why a CPU-proxy record is real evidence here.

The phase runs through the REAL bench runner (its own subprocess +
PhaseSpec.env 2-fake-device mesh + child-banked attested record) — the
exact path the daemon takes in production. Subprocess isolation is
also load-bearing: in this container's jax 0.4.37, compiling the same
tiny model on three meshes inside a process that already ran the full
suite aborts natively in the XLA CPU client (suite-state sensitivity;
standalone in-process runs pass) — the runner child sidesteps the
whole class, exactly as it does for real TPU windows.

Time budget: ~45 s (child imports + live compiles: the phase opts out
of the persistent XLA cache, see workloads._without_persistent_xla_cache);
tier-1 headroom is tracked per PR 7's discipline."""

import importlib.util
import json
import os

import pytest

from areal_tpu.bench import bank, runner
from tests.fixtures import scale_timeout

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

pytestmark = pytest.mark.serial


def _load_validator():
    spec = importlib.util.spec_from_file_location(
        "validate_bench", os.path.join(REPO, "scripts", "validate_bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.timeout(420)
def test_train_sharded_record_banks_and_validates(tmp_path, monkeypatch):
    b = str(tmp_path / "bank")
    monkeypatch.setenv("AREAL_BENCH_BANK", b)
    # The child gets exactly the phase's requested device topology (the
    # runner APPENDS PhaseSpec.env XLA_FLAGS to inherited ones; the
    # suite's 8-device conftest flag would otherwise ride along).
    monkeypatch.setenv("XLA_FLAGS", "")
    rec = runner.run_phase(
        "train_sharded", "measure", b, deadline_s=scale_timeout(360)
    )
    assert rec["status"] == "ok", rec
    bank.validate_record(rec)
    assert rec["attestation"]["platform"] == "cpu"
    assert rec["attestation"]["driver_verified"] is False

    validator = _load_validator()
    assert validator.validate_phase_value("train_sharded", rec) == []
    assert validator.validate_bank_dir(b) == []

    v = rec["value"]
    # THE acceptance numbers: mesh trajectories match the single-device
    # engine, and the shard-local dump halves the host high-water.
    assert v["fsdp2_parity_ok"] == 1.0 and v["tp2_parity_ok"] == 1.0
    assert v["loss_parity_max_rel_err"] < 5e-4
    assert v["dump_highwater_frac"] <= 0.6
    assert v["dump_roundtrip_ok"] == 1.0
    for k in ("single_step_s", "fsdp2_step_s", "tp2_step_s"):
        assert v[k] > 0  # the step-time breakdown banked

    # Validator teeth: records that lost the parity...
    bad = json.loads(json.dumps(rec))
    bad["value"]["tp2_parity_ok"] = 0.0
    assert any(
        "diverged" in p
        for p in validator.validate_phase_value("train_sharded", bad)
    )
    # ...whose dump did not shrink the high-water...
    bad = json.loads(json.dumps(rec))
    bad["value"]["dump_highwater_frac"] = 1.0
    assert any(
        "high-water" in p
        for p in validator.validate_phase_value("train_sharded", bad)
    )
    # ...or that lack the round-trip field entirely are refused.
    bad = json.loads(json.dumps(rec))
    del bad["value"]["dump_roundtrip_ok"]
    assert validator.validate_phase_value("train_sharded", bad)


def test_train_tflops_scaling_registered_and_schema_teeth():
    """The 1->N scaling phase is registered (default, driver-facing) so
    the daemon spends the next real TPU window on the curve — and the
    validator refuses curves without per-point per-chip numbers or not
    anchored at n_devices=1. Budget: <1 s (no phase body runs)."""
    from areal_tpu.bench import phases

    spec = phases.get("train_tflops_scaling")
    assert spec.default and not spec.proxy
    assert spec.priority < phases.get("pack_density").priority

    validator = _load_validator()
    rec = {
        "status": "ok", "pass": "measure",
        "value": {
            "n_devices_max": 2.0, "scaling_efficiency": 0.9,
            "points": [
                {"n_devices": 1.0, "step_s": 0.1,
                 "train_tflops_per_chip": 50.0},
                {"n_devices": 2.0, "step_s": 0.11,
                 "train_tflops_per_chip": 45.0},
            ],
        },
    }
    assert validator.validate_phase_value("train_tflops_scaling", rec) == []
    bad = json.loads(json.dumps(rec))
    bad["value"]["points"] = bad["value"]["points"][1:]  # no 1-chip anchor
    assert any(
        "n_devices == 1" in p
        for p in validator.validate_phase_value("train_tflops_scaling", bad)
    )
    bad = json.loads(json.dumps(rec))
    del bad["value"]["points"][0]["train_tflops_per_chip"]
    assert any(
        "train_tflops_per_chip" in p
        for p in validator.validate_phase_value("train_tflops_scaling", bad)
    )
    bad = json.loads(json.dumps(rec))
    del bad["value"]["points"]
    assert any(
        "points" in p
        for p in validator.validate_phase_value("train_tflops_scaling", bad)
    )

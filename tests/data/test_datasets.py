"""Dataset registry + jsonl dataset tests (counterpart of the reference's
tests/data/test_load_data.py category)."""

import numpy as np
import pytest

import areal_tpu.datasets  # noqa: F401  (registers datasets)
from areal_tpu.api import data_api
from tests import fixtures


@pytest.fixture(scope="module")
def tokenizer(tmp_path_factory):
    rows = fixtures.make_sft_rows(50, seed=7)
    texts = [r["prompt"] + " " + r["answer"] for r in rows]
    return fixtures.train_tiny_tokenizer(texts, tmp_path_factory.mktemp("tok"))


def _util(tokenizer, dp_rank=0, world_size=1, seed=1):
    return data_api.DatasetUtility(
        seed=seed, dp_rank=dp_rank, world_size=world_size, tokenizer=tokenizer
    )


def test_load_shuffle_split_partitions(tmp_path, tokenizer):
    rows = fixtures.make_sft_rows(23)
    path = fixtures.write_jsonl(rows, tmp_path / "d.jsonl")
    all_ids = set()
    sizes = []
    for dp in range(4):
        part = data_api.load_shuffle_split_dataset(
            data_api.DatasetUtility(seed=3, dp_rank=dp, world_size=4, tokenizer=None),
            path,
        )
        ids = {r["id"] for r in part}
        assert not (ids & all_ids), "DP slices must be disjoint"
        all_ids |= ids
        sizes.append(len(part))
    assert sum(sizes) == 23
    assert max(sizes) - min(sizes) <= 1
    assert all_ids == {r["id"] for r in rows}


def test_prompt_answer_dataset(tmp_path, tokenizer):
    rows = fixtures.make_sft_rows(12)
    path = fixtures.write_jsonl(rows, tmp_path / "sft.jsonl")
    from areal_tpu.datasets.prompt_answer import PromptAnswerDataset

    ds = PromptAnswerDataset(_util(tokenizer), max_length=64, dataset_path=path)
    assert len(ds) == 12
    s = ds[0]
    assert s.bs == 1
    assert {"packed_input_ids", "prompt_mask"} <= s.keys
    toks = s.data["packed_input_ids"]
    mask = s.data["prompt_mask"]
    assert len(toks) == len(mask) == s.sample_total_len(0)
    # prompt_mask True over a prefix only
    flips = np.diff(mask.astype(int))
    assert (flips <= 0).all()
    # answer region ends with EOS
    assert toks[-1] == tokenizer.eos_token_id


def test_prompt_dataset_and_loader(tmp_path, tokenizer):
    rows = fixtures.make_sft_rows(10)
    path = fixtures.write_jsonl(rows, tmp_path / "p.jsonl")
    from areal_tpu.datasets.prompt import PromptDataset

    ds = PromptDataset(_util(tokenizer), max_length=32, dataset_path=path)
    loader = data_api.PackedDataLoader(ds, batch_size=4, seed=5)
    seen = []
    last_flags = []
    for _ in range(len(loader)):
        batch, last = loader.next_batch()
        seen.extend(batch.ids)
        last_flags.append(last)
    assert sorted(seen) == sorted(str(r["id"]) for r in rows)
    assert last_flags == [False, False, True]
    assert loader.epoch == 1

    # Recovery round trip: same cursor -> same next batch.
    b1, _ = loader.next_batch()
    state = loader.state_dict()
    b2, _ = loader.next_batch()
    loader.load_state_dict(state)
    b3, _ = loader.next_batch()
    assert b2.ids == b3.ids


def test_rw_paired_dataset(tmp_path, tokenizer):
    rows = fixtures.make_rw_rows(8)
    path = fixtures.write_jsonl(rows, tmp_path / "rw.jsonl")
    from areal_tpu.datasets.rw_paired import RewardModelingPairedDataset

    ds = RewardModelingPairedDataset(
        _util(tokenizer), max_length=64, max_pairs_per_prompt=2, dataset_path=path
    )
    s = ds[0]
    lens = s.seqlens["packed_input_ids"][0]
    assert len(lens) % 2 == 0  # pos/neg pairs
    assert len(s.data["packed_input_ids"]) == sum(lens)
    assert s.data["group_factor"][0] == pytest.approx(1.0 / (len(lens) // 2))


def test_math_code_dataset_and_filter(tmp_path, tokenizer):
    rows = fixtures.make_math_code_rows(15)
    # Add one invalid row: must be skipped, not crash.
    rows.append({"query_id": "bad", "task": "math", "prompt": "x", "solutions": "notalist"})
    path = fixtures.write_jsonl(rows, tmp_path / "mc.jsonl")
    from areal_tpu.datasets.math_code_prompt import MATHCodePromptDataset, load_metadata

    id2info, task_cnt = load_metadata(path)
    assert len(id2info) == 15
    assert task_cnt["math"] == 10 and task_cnt["code"] == 5

    ds = MATHCodePromptDataset(
        _util(tokenizer),
        max_length=64,
        dataset_path=path,
        filter_threshold=0.8,
        max_filter_percentage=0.2,
    )
    assert len(ds) == 15
    s = ds[0]
    assert {"packed_prompts", "task_ids"} <= s.keys
    assert s.data["task_ids"][0] in (0, 1, 3)

    # Curriculum filter: 20% cap -> 3 of the 4 high scorers dropped.
    ids = [ds.ids[i] for i in ds.active_indices]
    scores = {ids[i]: 1.0 for i in range(4)}
    ds.filter(scores)
    assert len(ds) == 12


def test_registry_construction(tmp_path, tokenizer):
    from areal_tpu.api.config import DatasetAbstraction

    rows = fixtures.make_sft_rows(6)
    path = fixtures.write_jsonl(rows, tmp_path / "r.jsonl")
    ds = data_api.make_dataset(
        DatasetAbstraction("prompt_answer", args=dict(max_length=32, dataset_path=path)),
        util=_util(tokenizer),
    )
    assert len(ds) == 6


def test_loader_survives_mid_epoch_shrink(tmp_path, tokenizer):
    """Curriculum filter shrinking the dataset mid-epoch must not crash or
    repeat samples past the end (regression: cursor outliving a regenerated
    permutation)."""
    rows = fixtures.make_math_code_rows(10)
    path = fixtures.write_jsonl(rows, tmp_path / "mc.jsonl")
    from areal_tpu.datasets.math_code_prompt import MATHCodePromptDataset

    ds = MATHCodePromptDataset(_util(tokenizer), dataset_path=path)
    loader = data_api.PackedDataLoader(ds, batch_size=4, seed=3)
    loader.next_batch()
    loader.next_batch()  # cursor = 8 of 10
    ds.active_indices = ds.active_indices[:5]  # simulate aggressive filter
    batch, _ = loader.next_batch()  # must not crash
    assert 1 <= batch.bs <= 4

    # Checkpoint from the larger dataset restored onto the smaller one.
    state = dict(epoch=0, cursor=8, seed=3, size=10)
    loader.load_state_dict(state)
    batch, _ = loader.next_batch()
    assert 1 <= batch.bs <= 4


def test_prompt_mask_is_exact_token_prefix(tmp_path, tokenizer):
    """The masked prefix must decode back to exactly the prompt's tokens
    (regression: joint tokenization merging across the boundary)."""
    rows = fixtures.make_sft_rows(8, seed=11)
    path = fixtures.write_jsonl(rows, tmp_path / "sft.jsonl")
    from areal_tpu.datasets.prompt_answer import PromptAnswerDataset

    ds = PromptAnswerDataset(_util(tokenizer), max_length=64, dataset_path=path)
    prompt_encs = {
        str(r["id"]): tokenizer(r["prompt"], add_special_tokens=True)["input_ids"]
        for r in rows
    }
    for i in range(len(ds)):
        s = ds[i]
        toks = list(s.data["packed_input_ids"])
        mask = s.data["prompt_mask"]
        plen = int(mask.sum())
        assert toks[:plen] == prompt_encs[s.ids[0]][:plen]


def test_rw_paired_deterministic_reads(tmp_path, tokenizer):
    rows = fixtures.make_rw_rows(6, seed=2)
    path = fixtures.write_jsonl(rows, tmp_path / "rw.jsonl")
    from areal_tpu.datasets.rw_paired import RewardModelingPairedDataset

    ds = RewardModelingPairedDataset(
        _util(tokenizer), max_length=64, max_pairs_per_prompt=2, dataset_path=path
    )
    for i in range(len(ds)):
        a, b = ds[i], ds[i]
        np.testing.assert_array_equal(
            a.data["packed_input_ids"], b.data["packed_input_ids"]
        )
    # A rebuilt dataset returns identical data (recovery determinism).
    ds2 = RewardModelingPairedDataset(
        _util(tokenizer), max_length=64, max_pairs_per_prompt=2, dataset_path=path
    )
    np.testing.assert_array_equal(
        ds[0].data["packed_input_ids"], ds2[0].data["packed_input_ids"]
    )


def test_auto_id_no_collision(tmp_path, tokenizer):
    rows = [
        {"prompt": "alpha beta", "id": 3},
        {"prompt": "gamma delta"},  # missing id at index 1
        {"prompt": "eps zeta"},
    ]
    path = fixtures.write_jsonl(rows, tmp_path / "p.jsonl")
    part = data_api.load_shuffle_split_dataset(
        data_api.DatasetUtility(seed=3, dp_rank=0, world_size=1, tokenizer=None), path
    )
    ids = [str(r["id"]) for r in part]
    assert len(set(ids)) == 3

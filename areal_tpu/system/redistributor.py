"""Master-side data-location tracking and transfer planning.

Counterpart of the reference's redistributor (realhf/system/
redistributor.py:12-360). The master tracks which model worker owns each
(sample_id, key) and, when an MFC dispatches a batch to its DP workers,
derives per-destination pull plans. On GPU the reference executes plans
as NCCL gather/scatter/bcast; here transfers are host-side peer pulls
over ZMQ (token-scale arrays — device-resident tensors never move
through this plane), executed by `areal_tpu.system.data_manager`.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Set, Tuple


@dataclasses.dataclass
class RedistribStep:
    """One transfer: dst worker pulls `keys` of `ids` from src worker."""

    src: str
    dst: str
    ids: List[str]
    keys: List[str]


class GlobalStorageTracker:
    """(sample_id, key) -> owner worker names (reference redistributor.py:12)."""

    def __init__(self):
        self.storage: Dict[Tuple[str, str], Set[str]] = {}

    def add(self, sample_id: str, key: str, worker: str):
        self.storage.setdefault((sample_id, key), set()).add(worker)

    def add_batch(self, sample_ids: List[str], keys: List[str], worker: str):
        for i in sample_ids:
            for k in keys:
                self.add(i, k, worker)

    def owners(self, sample_id: str, key: str) -> Set[str]:
        return self.storage.get((sample_id, key), set())

    def drop_samples(self, sample_ids: List[str]):
        ids = set(sample_ids)
        self.storage = {
            (i, k): v for (i, k), v in self.storage.items() if i not in ids
        }

    def clear(self):
        self.storage.clear()


class RedistribPlanner:
    """Derive pull plans (reference derive_plan_gather_scatter:91).

    For each destination worker and each (id, key) it needs but does not
    own, pick one owner (prefer the destination itself, then round-robin
    across owners for load balance) and emit per-(src,dst) merged steps.
    """

    def __init__(self, tracker: GlobalStorageTracker):
        self.tracker = tracker
        self._rr = 0

    def derive_plan(
        self,
        dests: Dict[str, List[str]],  # dst worker -> sample ids it needs
        keys: List[str],
    ) -> List[RedistribStep]:
        steps: Dict[Tuple[str, str], Dict[str, List[str]]] = {}
        for dst, ids in dests.items():
            for sample_id in ids:
                for key in keys:
                    owners = self.tracker.owners(sample_id, key)
                    if not owners:
                        raise ValueError(
                            f"no owner for (id={sample_id}, key={key})"
                        )
                    if dst in owners:
                        continue
                    src = sorted(owners)[self._rr % len(owners)]
                    self._rr += 1
                    bucket = steps.setdefault((src, dst), {})
                    bucket.setdefault(key, []).append(sample_id)
        plan: List[RedistribStep] = []
        for (src, dst), by_key in steps.items():
            # Group keys that share the same id list into one step.
            sig: Dict[Tuple[str, ...], List[str]] = {}
            for key, ids in by_key.items():
                sig.setdefault(tuple(ids), []).append(key)
            for ids, ks in sig.items():
                plan.append(RedistribStep(src=src, dst=dst, ids=list(ids), keys=ks))
        return plan
